"""Advanced math / munging rapids prims — second wave toward the reference's
~200-primitive surface (`water/rapids/ast/prims/{advmath,mungers,matrix}`).

Device-friendly ops (quantile, scale, cut, diff, moments, correlation) run as
jnp reductions over the sharded columns; the index-shuffling munging ops
(pivot/melt/rank/match) assemble on host — they are metadata-sized or
permutation-bound, the same ops the reference runs as single-node or
low-arithmetic MRTasks."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, T_INT, T_NUM, T_STR, T_TIME, Vec


def _valid_np(v: Vec) -> np.ndarray:
    x = v.to_numpy()
    return x, ~np.isnan(x)


# ---------------------------------------------------------------------------
# moments / correlation (`AstSkewness`, `AstKurtosis`, `AstCorrelation`)
# ---------------------------------------------------------------------------
def skewness(v: Vec, na_rm: bool = True) -> float:
    x = v.data
    ok = (~jnp.isnan(x)) & (jnp.arange(x.shape[0]) < v.nrow)
    n = jnp.sum(ok)
    mu = jnp.sum(jnp.where(ok, x, 0)) / n
    d = jnp.where(ok, x - mu, 0.0)
    m2 = jnp.sum(d * d) / (n - 1)
    m3 = jnp.sum(d ** 3) / n
    return float(m3 / jnp.power(m2, 1.5))


def kurtosis(v: Vec, na_rm: bool = True) -> float:
    x = v.data
    ok = (~jnp.isnan(x)) & (jnp.arange(x.shape[0]) < v.nrow)
    n = jnp.sum(ok)
    mu = jnp.sum(jnp.where(ok, x, 0)) / n
    d = jnp.where(ok, x - mu, 0.0)
    m2 = jnp.sum(d * d) / (n - 1)
    m4 = jnp.sum(d ** 4) / n
    return float(m4 / (m2 * m2))


def _rank_frame(fr: Frame) -> Frame:
    """Average-rank transform per column (ties → midranks), the Spearman
    front-end (`advmath/SpearmanCorrelation.java` rank MRTask)."""
    from scipy.stats import rankdata

    cols = {}
    for n in fr.names:
        x = fr.vec(n).to_numpy()
        r = np.full_like(x, np.nan, dtype=np.float64)
        ok = ~np.isnan(x)
        r[ok] = rankdata(x[ok])
        cols[n] = r.astype(np.float32)
    return Frame(list(fr.names), [Vec.from_numpy(v) for v in cols.values()])


def cor(fx: Frame, fy: Frame, use: str = "everything",
        method: str = "Pearson"):
    """Pairwise Pearson/Spearman correlation; complete-rows handling like the
    reference's 'complete.obs'. Returns a float for 1x1, else a Frame."""
    if str(method).lower().startswith("spearman"):
        # Spearman = Pearson over midrank-transformed columns; ranks are
        # computed AFTER dropping incomplete rows so they stay contiguous
        # (matches R's complete.obs and `SpearmanCorrelation.java`)
        ok = np.ones(fx.vec(0).nrow, dtype=bool)
        for f in (fx, fy):
            for i in range(f.ncol):
                ok &= ~np.isnan(f.vec(i).to_numpy())
        idx = np.where(ok)[0]
        return cor(_rank_frame(fx.take(idx)), _rank_frame(fy.take(idx)),
                   use, "Pearson")
    Xc = [fx.vec(i) for i in range(fx.ncol)]
    Yc = [fy.vec(i) for i in range(fy.ncol)]
    X = jnp.stack([c.data for c in Xc], axis=1)
    Y = jnp.stack([c.data for c in Yc], axis=1)
    nrow = Xc[0].nrow
    inr = jnp.arange(X.shape[0]) < nrow
    ok = inr & ~jnp.any(jnp.isnan(X), axis=1) & ~jnp.any(jnp.isnan(Y), axis=1)
    n = jnp.sum(ok)
    Xz = jnp.where(ok[:, None], X, 0.0)
    Yz = jnp.where(ok[:, None], Y, 0.0)
    mx = jnp.sum(Xz, axis=0) / n
    my = jnp.sum(Yz, axis=0) / n
    Xd = jnp.where(ok[:, None], X - mx, 0.0)
    Yd = jnp.where(ok[:, None], Y - my, 0.0)
    cov = Xd.T @ Yd / (n - 1)
    sx = jnp.sqrt(jnp.sum(Xd * Xd, axis=0) / (n - 1))
    sy = jnp.sqrt(jnp.sum(Yd * Yd, axis=0) / (n - 1))
    C = cov / jnp.outer(sx, sy)
    if C.shape == (1, 1):
        return float(C[0, 0])
    out = np.asarray(C)
    return Frame(list(fy.names),
                 [Vec.from_numpy(out[:, j].astype(np.float32))
                  for j in range(out.shape[1])])


def quantile_frame(fr: Frame, probs, interpolation: str = "interpolate") -> Frame:
    """`AstQtile` (type 7 linear interpolation, NAs skipped)."""
    probs = [probs] if isinstance(probs, float) else list(probs)
    cols = {"Probs": Vec.from_numpy(np.asarray(probs, dtype=np.float32))}
    for name in fr.names:
        v = fr.vec(name)
        x, ok = _valid_np(v)
        xs = np.sort(x[ok])
        if xs.size == 0:
            q = np.full(len(probs), np.nan)
        else:
            q = np.quantile(xs, probs,
                            method="linear" if interpolation != "low"
                            else "lower")
        # float64 out: from_numpy keeps an exact sidecar when f32 is lossy,
        # so the client reads full-precision quantiles (the reference is
        # float64 end-to-end)
        cols[f"{name}Quantiles"] = Vec.from_numpy(q.astype(np.float64))
    return Frame(list(cols), list(cols.values()))


# ---------------------------------------------------------------------------
# imputation / scaling / NA handling (`AstImpute`, `AstScale`, `AstNaOmit`,
# `AstFillNA`)
# ---------------------------------------------------------------------------
def _column_stat(x: np.ndarray, ok: np.ndarray, method: str) -> float:
    if not ok.any():
        return np.nan
    if method == "median":
        return float(np.median(x[ok]))
    if method == "mode":
        vals, cnt = np.unique(x[ok], return_counts=True)
        return float(vals[np.argmax(cnt)])
    return float(np.mean(x[ok]))


def impute(fr: Frame, col: int, method: str = "mean",
           combine_method: str = "interpolate", gb_cols=None,
           values=None) -> list[float]:
    """In-place column imputation, optionally per group (`AstImpute`);
    returns the fill value(s) (global path) or the per-group fills."""
    method = (method or "mean").lower()
    idxs = range(fr.ncol) if col is None or col < 0 else [int(col)]
    gb_cols = [] if gb_cols in (None, [], "_") else (
        gb_cols if isinstance(gb_cols, list) else [gb_cols])
    gkeys = None
    if gb_cols:
        G = np.stack([fr.vec(int(c) if isinstance(c, float) else c).to_numpy()
                      for c in gb_cols], axis=1)
        _, gkeys = np.unique(G, axis=0, return_inverse=True)
    fills = []
    for ci in idxs:
        v = fr.vec(ci)
        if v.is_categorical() and method == "mean":
            raise ValueError("mean imputation on a categorical column — "
                             "use method='mode' (AstImpute restriction)")
        x, ok = _valid_np(v)
        if values not in (None, []) and not isinstance(values, str):
            fill = float(values[len(fills)] if isinstance(values, list)
                         else values)
            filled = np.where(ok, x, fill)
            fills.append(fill)
        elif gkeys is not None:
            filled = x.copy()
            group_fills = {}
            for g in np.unique(gkeys):
                sel = gkeys == g
                f = _column_stat(x, ok & sel, method)
                group_fills[int(g)] = f
                filled[sel & ~ok] = f
            fills.append(group_fills)
        else:
            fill = _column_stat(x, ok, method)
            filled = np.where(ok, x, fill)
            fills.append(fill)
        fr.replace(fr.names[ci], Vec.from_numpy(filled, type=v.type,
                                                domain=v.domain))
    return fills


def scale_frame(fr: Frame, center=True, scale=True) -> Frame:
    """(x - center)/scale per numeric column; center/scale may be bools or
    per-column number lists (`AstScale`)."""
    out = Frame([], [])
    num_i = 0
    for name in fr.names:
        v = fr.vec(name)
        if v.is_categorical() or v.data is None:
            out.add(name, v)
            continue
        r = v.rollups()
        if isinstance(center, list):
            c = float(center[num_i])
        else:
            c = float(r.mean) if center else 0.0
        if isinstance(scale, list):
            s = float(scale[num_i])
        else:
            s = float(r.sigma) if scale else 1.0
        s = s if s > 0 else 1.0
        out.add(name, Vec((v.data - c) / s, v.nrow))
        num_i += 1
    return out


def na_omit(fr: Frame) -> Frame:
    keep = np.ones(fr.nrow, dtype=bool)
    for i in range(fr.ncol):
        x = fr.vec(i).to_numpy()
        if x is not None and x.dtype != object:
            keep &= ~np.isnan(x)
        else:
            keep &= np.array([s is not None for s in fr.vec(i).host_data])
    return fr.take(np.where(keep)[0])


def _ffill_1d(x: np.ndarray, maxlen: int) -> np.ndarray:
    idx = np.arange(len(x))
    ok = ~np.isnan(x)
    last = np.maximum.accumulate(np.where(ok, idx, -1))
    dist = idx - last
    can = (last >= 0) & (dist > 0) & (dist <= maxlen)
    return np.where(can, x[np.clip(last, 0, None)], x)


def fillna(fr: Frame, method: str = "forward", axis: int = 0,
           maxlen: int = 1) -> Frame:
    """`AstFillNA`: propagate last (or next) valid value up to maxlen cells,
    down the rows (axis=0) or across the columns (axis=1). Exact-int64/time
    columns keep their original dtype (Vec.from_numpy retains the exact
    copy when f32 would be lossy)."""
    back = method.lower() in ("backward", "bfill")
    if axis == 1:
        numeric = [n for n in fr.names if fr.vec(n).data is not None
                   and not fr.vec(n).is_categorical()]
        X = np.stack([fr.vec(n).to_numpy().astype(np.float64)
                      for n in numeric], axis=1)
        if back:
            X = X[:, ::-1]
        idx = np.arange(X.shape[1])[None, :]
        ok = ~np.isnan(X)
        last = np.maximum.accumulate(np.where(ok, idx, -1), axis=1)
        dist = idx - last
        can = (last >= 0) & (dist > 0) & (dist <= maxlen)
        X = np.where(can, np.take_along_axis(X, np.clip(last, 0, None),
                                             axis=1), X)
        if back:
            X = X[:, ::-1]
        out = Frame([], [])
        ji = 0
        for n in fr.names:
            v = fr.vec(n)
            if n in numeric:
                out.add(n, Vec.from_numpy(X[:, ji], type=v.type,
                                          domain=v.domain))
                ji += 1
            else:
                out.add(n, v)
        return out
    out = Frame([], [])
    for name in fr.names:
        v = fr.vec(name)
        x = v.to_numpy().copy()
        if x is None or x.dtype == object:
            out.add(name, v)
            continue
        filled = _ffill_1d(x[::-1], maxlen)[::-1] if back \
            else _ffill_1d(x, maxlen)
        out.add(name, Vec.from_numpy(filled, type=v.type, domain=v.domain))
    return out


# ---------------------------------------------------------------------------
# which / match / cut / diff (`AstWhich*`, `AstMatch`, `AstCut`, `AstDiffLag1`)
# ---------------------------------------------------------------------------
def which(v: Vec) -> Vec:
    x, ok = _valid_np(v)
    # int64 indices: Vec.from_numpy keeps the exact copy when f32 is lossy
    return Vec.from_numpy(np.where(ok & (x != 0))[0], type=T_INT)


def which_extreme(fr: Frame, na_rm: bool = True, axis: int = 0,
                  mx: bool = True) -> Frame:
    """Per-column (axis=0) or per-row (axis=1) arg-extreme (`AstWhichMax`)."""
    key = "which.max" if mx else "which.min"
    if axis == 1:
        X = np.stack([fr.vec(n).to_numpy() for n in fr.names], axis=1)
        ok = ~np.isnan(X)
        Xm = np.where(ok, X, -np.inf if mx else np.inf)
        idx = (np.argmax(Xm, axis=1) if mx
               else np.argmin(Xm, axis=1)).astype(np.float64)
        idx[~ok.any(axis=1)] = np.nan
        return Frame([key], [Vec.from_numpy(idx)])
    idxs = []
    for name in fr.names:
        x, ok = _valid_np(fr.vec(name))
        if not ok.any():
            idxs.append(np.nan)
        else:
            xm = np.where(ok, x, -np.inf if mx else np.inf)
            idxs.append(float(np.argmax(xm) if mx else np.argmin(xm)))
    return Frame([key], [Vec.from_numpy(np.asarray(idxs, dtype=np.float64))])


def match(v: Vec, table, nomatch=np.nan, start_index: float = 1.0) -> Vec:
    """Map values/levels to their 1-based position in `table` (`AstMatch`)."""
    table = [table] if isinstance(table, (str, float)) else list(table)
    x = v.to_numpy()
    out = np.full(len(x), np.nan if nomatch is None else float(nomatch),
                  dtype=np.float32)
    if v.is_categorical() and v.domain:
        lut = {}
        for pos, t in enumerate(table):
            lut.setdefault(str(t), pos + start_index)
        codes = {i: lut.get(lvl) for i, lvl in enumerate(v.domain)}
        ok = ~np.isnan(x)
        for i, hit in codes.items():
            if hit is not None:
                out[ok & (x == i)] = hit
    else:
        for pos, t in enumerate(table):
            out[x == float(t)] = pos + start_index
    return Vec.from_numpy(out)


def cut(v: Vec, breaks, labels=None, include_lowest=False, right=True,
        dig_lab: int = 3) -> Vec:
    """Numeric → categorical binning (`AstCut`)."""
    breaks = np.asarray(breaks, dtype=np.float64)
    x = v.to_numpy()
    b = jnp.searchsorted(jnp.asarray(breaks),
                         jnp.asarray(np.nan_to_num(x, nan=np.inf)),
                         side="left" if right else "right")
    codes = np.asarray(b, dtype=np.float64) - 1
    oob = np.isnan(x) | (x > breaks[-1]) | \
        ((x <= breaks[0]) if (right and not include_lowest) else (x < breaks[0]))
    if not right:
        oob |= x >= breaks[-1]   # last interval is right-open
    if right and include_lowest:
        codes[x == breaks[0]] = 0
    codes = np.clip(codes, 0, len(breaks) - 2)
    codes[oob] = np.nan
    if labels in (None, []):
        fmt = lambda a: f"%.{dig_lab}g" % a
        labels = [f"({fmt(breaks[i])},{fmt(breaks[i+1])}]" if right else
                  f"[{fmt(breaks[i])},{fmt(breaks[i+1])})"
                  for i in range(len(breaks) - 1)]
    return Vec.from_numpy(codes.astype(np.float32), type=T_CAT,
                          domain=[str(l) for l in labels])


def difflag1(v: Vec) -> Vec:
    """x[i] − x[i−1], first row NA (`AstDiffLag1`)."""
    x = v.data
    out = jnp.concatenate([jnp.array([jnp.nan]), x[1:] - x[:-1]])
    return Vec.from_device(out, v.nrow)


# ---------------------------------------------------------------------------
# fold / split columns (`AstKFold`, `AstStratifiedKFold`, `AstStratifiedSplit`)
# ---------------------------------------------------------------------------
def kfold_column(v: Vec, nfolds: int, seed: int = -1) -> Vec:
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    folds = rng.permutation(np.arange(v.nrow) % int(nfolds))
    return Vec.from_numpy(folds.astype(np.float32), type=T_INT)


def stratified_kfold_column(y: Vec, nfolds: int, seed: int = -1) -> Vec:
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    x = y.to_numpy()
    out = np.zeros(y.nrow, dtype=np.float32)
    for lvl in np.unique(x[~np.isnan(x)]):
        idx = np.where(x == lvl)[0]
        out[rng.permutation(idx)] = np.arange(len(idx)) % int(nfolds)
    return Vec.from_numpy(out, type=T_INT)


def stratified_split(y: Vec, test_frac: float = 0.2, seed: int = -1) -> Vec:
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    x = y.to_numpy()
    out = np.zeros(y.nrow, dtype=np.float32)
    for lvl in np.unique(x[~np.isnan(x)]):
        idx = rng.permutation(np.where(x == lvl)[0])
        out[idx[:int(round(test_frac * len(idx)))]] = 1.0
    return Vec.from_numpy(out, type=T_CAT, domain=["train", "test"])


# ---------------------------------------------------------------------------
# levels / relevel (`AstLevels`, `AstRelevel`, `AstSetDomain`)
# ---------------------------------------------------------------------------
def levels(fr: Frame) -> list:
    return [list(fr.vec(i).domain or []) for i in range(fr.ncol)]


def relevel(v: Vec, level: str) -> Vec:
    if not v.is_categorical():
        raise ValueError("relevel requires a categorical column")
    dom = list(v.domain)
    if level not in dom:
        raise ValueError(f"level '{level}' not in domain")
    new_dom = [level] + [d for d in dom if d != level]
    remap = np.array([new_dom.index(d) for d in dom], dtype=np.float32)
    x = v.to_numpy()
    ok = ~np.isnan(x)
    out = np.full(len(x), np.nan, dtype=np.float32)
    out[ok] = remap[x[ok].astype(np.int64)]
    return Vec.from_numpy(out, type=T_CAT, domain=new_dom)


def set_domain(v: Vec, labels) -> Vec:
    return Vec(v.data, v.nrow, type=T_CAT, domain=[str(l) for l in labels])


# ---------------------------------------------------------------------------
# reshape (`AstPivot`, `AstMelt`, `AstTranspose`, `AstMMult`)
# ---------------------------------------------------------------------------
def pivot(fr: Frame, index: str, column: str, value: str) -> Frame:
    idx_v, col_v, val_v = (fr.vec(n) for n in (index, column, value))
    ivals = idx_v.to_numpy()
    cvals = col_v.to_numpy()
    vvals = val_v.to_numpy()
    uidx = np.unique(ivals[~np.isnan(ivals)])
    cdom = col_v.domain if col_v.is_categorical() else \
        [str(x) for x in np.unique(cvals[~np.isnan(cvals)])]
    ccodes = cvals if col_v.is_categorical() else \
        np.searchsorted(np.unique(cvals[~np.isnan(cvals)]), cvals)
    out = np.full((len(uidx), len(cdom)), np.nan, dtype=np.float64)
    pos = np.searchsorted(uidx, ivals)
    ok = ~np.isnan(ivals) & ~np.isnan(cvals)
    out[pos[ok], ccodes[ok].astype(np.int64)] = vvals[ok]
    cols = {index: Vec.from_numpy(uidx, type=idx_v.type,
                                  domain=idx_v.domain)}
    for j, c in enumerate(cdom):
        cols[str(c)] = Vec.from_numpy(out[:, j])
    return Frame(list(cols), list(cols.values()))


def melt(fr: Frame, id_vars, value_vars=None, var_name: str = "variable",
         value_name: str = "value", skipna: bool = False) -> Frame:
    id_vars = [id_vars] if isinstance(id_vars, str) else list(id_vars)
    value_vars = value_vars or [n for n in fr.names if n not in id_vars]
    value_vars = [value_vars] if isinstance(value_vars, str) else list(value_vars)
    n = fr.nrow
    ids = {c: fr.vec(c).to_numpy() for c in id_vars}
    var_codes, vals = [], []
    keep = []
    for vi, c in enumerate(value_vars):
        x = fr.vec(c).to_numpy()
        m = ~np.isnan(x) if skipna else np.ones(n, dtype=bool)
        keep.append(m)
        var_codes.append(np.full(int(m.sum()), vi, dtype=np.float32))
        vals.append(x[m])
    cols = {}
    for c in id_vars:
        v = fr.vec(c)
        cols[c] = Vec.from_numpy(
            np.concatenate([ids[c][m] for m in keep]),
            type=v.type, domain=v.domain)
    cols[var_name] = Vec.from_numpy(np.concatenate(var_codes), type=T_CAT,
                                    domain=[str(c) for c in value_vars])
    cols[value_name] = Vec.from_numpy(np.concatenate(vals))
    return Frame(list(cols), list(cols.values()))


def transpose(fr: Frame) -> Frame:
    # to_numpy returns the exact f64 sidecar when present; keep that
    # precision through the transpose (from_numpy re-derives sidecars)
    X = np.stack([fr.vec(i).to_numpy().astype(np.float64)
                  for i in range(fr.ncol)], axis=0)
    return Frame([f"C{i+1}" for i in range(X.shape[1])],
                 [Vec.from_numpy(X[:, i]) for i in range(X.shape[1])])


def mmult(fx: Frame, fy: Frame) -> Frame:
    # f64 host path when either side carries exact sidecars (values that
    # don't round-trip f32) — the reference multiplies doubles; device f32
    # (MXU) remains the path for exactly-representable data
    if any(fx.vec(i).exact_data is not None for i in range(fx.ncol)) or \
            any(fy.vec(i).exact_data is not None for i in range(fy.ncol)):
        X = np.stack([fx.vec(i).to_numpy().astype(np.float64)
                      for i in range(fx.ncol)], axis=1)
        Y = np.stack([fy.vec(i).to_numpy().astype(np.float64)
                      for i in range(fy.ncol)], axis=1)
        Z = X @ Y
        return Frame([f"C{i+1}" for i in range(Z.shape[1])],
                     [Vec.from_numpy(Z[:, i]) for i in range(Z.shape[1])])
    X = jnp.stack([fx.vec(i).data[:fx.nrow] for i in range(fx.ncol)], axis=1)
    Y = jnp.stack([fy.vec(i).data[:fy.nrow] for i in range(fy.ncol)], axis=1)
    Z = np.asarray(X @ Y)
    return Frame([f"C{i+1}" for i in range(Z.shape[1])],
                 [Vec.from_numpy(Z[:, i].astype(np.float32))
                  for i in range(Z.shape[1])])


# ---------------------------------------------------------------------------
# rank within group (`AstRankWithinGroupBy`)
# ---------------------------------------------------------------------------
def rank_within_group_by(fr: Frame, group_cols, sort_cols, ascending=None,
                         new_col_name: str = "New_Rank_column") -> Frame:
    group_cols = [group_cols] if isinstance(group_cols, (str, float)) else group_cols
    sort_cols = [sort_cols] if isinstance(sort_cols, (str, float)) else sort_cols
    gnames = [fr.names[int(c)] if isinstance(c, float) else c for c in group_cols]
    snames = [fr.names[int(c)] if isinstance(c, float) else c for c in sort_cols]
    asc = ascending if ascending not in (None, []) else [1.0] * len(snames)
    G = np.stack([fr.vec(n).to_numpy() for n in gnames], axis=1)
    S = np.stack([fr.vec(n).to_numpy() * (1 if a else -1)
                  for n, a in zip(snames, asc)], axis=1)
    order = np.lexsort(tuple(S[:, i] for i in reversed(range(S.shape[1])))
                       + tuple(G[:, i] for i in reversed(range(G.shape[1]))))
    ranks = np.full(fr.nrow, np.nan, dtype=np.float32)
    prev = None
    r = 0
    for pos in order:
        gkey = tuple(G[pos])
        if any(np.isnan(S[pos])):
            continue
        if gkey != prev:
            r = 1
            prev = gkey
        else:
            r += 1
        ranks[pos] = r
    out = Frame(list(fr.names), list(fr.vecs))
    out.add(new_col_name, Vec.from_numpy(ranks))
    return out


# ---------------------------------------------------------------------------
# topn (`AstTopN`)
# ---------------------------------------------------------------------------
def topn(fr: Frame, col: int, npercent: float, bottom: bool = False) -> Frame:
    v = fr.vec(int(col))
    x, ok = _valid_np(v)
    n = max(1, int(round(npercent / 100.0 * v.nrow)))
    idx = np.where(ok)[0]
    order = idx[np.argsort(x[idx])]
    pick = order[:n] if bottom else order[::-1][:n]
    name = "Bottom" if bottom else "Top"
    # original dtypes through from_numpy: exact int64/time values survive
    return Frame(["Row Indices", f"{name} {fr.names[int(col)]} values"],
                 [Vec.from_numpy(pick, type=T_INT),
                  Vec.from_numpy(x[pick])])


# ---------------------------------------------------------------------------
# factor interactions (`hex/Interaction` / `h2o.interaction`)
# ---------------------------------------------------------------------------
def interaction(fr: Frame, factors, pairwise: bool = False,
                max_factors: int = 100, min_occurrence: int = 1) -> Frame:
    """Combined categorical columns from factor tuples: top `max_factors`
    observed combos (≥ min_occurrence) become levels, the tail becomes
    'other'."""
    factors = [factors] if isinstance(factors, str) else list(factors)
    names = [fr.names[int(f)] if isinstance(f, float) else f for f in factors]
    groups = ([[a, b] for i, a in enumerate(names) for b in names[i + 1:]]
              if pairwise and len(names) > 2 else [names])
    out = Frame([], [])
    for grp in groups:
        vs = [fr.vec(n) for n in grp]
        for v, n in zip(vs, grp):
            if not v.is_categorical():
                raise ValueError(f"interaction: column '{n}' is not "
                                 f"categorical")
        # vectorized combo coding: stack code columns, NA row-mask, then one
        # np.unique over complete rows builds the observed-combo table
        codes = np.stack([v.to_numpy() for v in vs], axis=1)
        ok = ~np.isnan(codes).any(axis=1)
        combos = codes[ok].astype(np.int64)
        uniq, inverse, counts = np.unique(
            combos, axis=0, return_inverse=True, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        keep_n = int(min(max_factors,
                         int(np.sum(counts >= min_occurrence))))
        kept_ids = order[:keep_n][counts[order[:keep_n]] >= min_occurrence]
        dom = ["_".join(v.domain[c] for v, c in zip(vs, uniq[u]))
               for u in kept_ids]
        has_other = len(uniq) > len(kept_ids)
        if has_other:
            dom.append("other")
        remap = np.full(len(uniq), float(len(kept_ids)))  # default → other
        remap[kept_ids] = np.arange(len(kept_ids), dtype=np.float64)
        col = np.full(fr.nrow, np.nan, dtype=np.float32)
        col[ok] = remap[inverse]
        out.add("_".join(grp), Vec.from_numpy(col, type=T_CAT, domain=dom))
    return out


# ---------------------------------------------------------------------------
# time construction (`AstMoment`, `AstMktime`)
# ---------------------------------------------------------------------------
def moment(year, month, day, hour=0.0, minute=0.0, second=0.0, msec=0.0) -> Vec:
    def arr(a):
        if isinstance(a, Vec):
            return a.to_numpy().astype(np.float64)
        return np.asarray([float(a)])
    ys, ms, ds, hs, mins, ss, mss = (arr(a) for a in
                                     (year, month, day, hour, minute, second,
                                      msec))
    n = max(map(len, (ys, ms, ds, hs, mins, ss, mss)))
    def bc(a):
        return np.broadcast_to(a, (n,)) if len(a) != n else a
    ys, ms, ds, hs, mins, ss, mss = map(bc, (ys, ms, ds, hs, mins, ss, mss))
    out = np.full(n, np.nan, dtype=np.float64)
    for i in range(n):
        try:
            dt = np.datetime64(
                f"{int(ys[i]):04d}-{int(ms[i]):02d}-{int(ds[i]):02d}"
                f"T{int(hs[i]):02d}:{int(mins[i]):02d}:{int(ss[i]):02d}", "ms")
            out[i] = dt.astype("int64") + mss[i]
        except Exception:
            pass
    # float64 in: Vec keeps an exact host copy when f32 would be lossy
    # (ms-since-epoch exceeds 2^24)
    return Vec.from_numpy(out, type=T_TIME)


# ---------------------------------------------------------------------------
# Tabulate (`water/util/Tabulate`, `POST /99/Tabulate`)
# ---------------------------------------------------------------------------
def tabulate(fr: Frame, predictor: str, response: str,
             weight: str | None = None, nbins_predictor: int = 20,
             nbins_response: int = 10):
    """Co-occurrence tabulation of predictor vs response: a weighted count
    grid over (x-bin, y-bin) and the per-x-bin weighted response mean —
    `Tabulate.execImpl`'s two tables. Categoricals keep one bin per level,
    numerics bin uniformly over [min,max], missing values get a leading
    "missing(NA)" bin when present (the reference's `_missing` offset)."""
    from ..utils.twodimtable import TwoDimTable

    if nbins_predictor < 1 or nbins_response < 1:
        raise ValueError("number of bins must be >= 1")
    vx, vy = fr.vec(predictor), fr.vec(response)
    if vx is None or vy is None:
        missing = predictor if vx is None else response
        raise KeyError(f"column {missing} not found")
    w = (fr.vec(weight).to_numpy() if weight else
         np.ones(fr.nrow, dtype=np.float64))

    def axis(v, nbins):
        x = v.to_numpy().astype(np.float64)
        has_na = bool(np.isnan(x).any())
        if v.domain is not None:
            nb = v.cardinality()
            bins = np.where(np.isnan(x), -1, x).astype(np.int64)
            labels = list(v.domain)
        else:
            lo, hi = np.nanmin(x), np.nanmax(x)
            if v.type == T_INT and (hi - lo + 1) <= nbins:
                nb = int(hi - lo + 1)
                bins = np.where(np.isnan(x), -1, x - lo).astype(np.int64)
                labels = [str(int(lo + b)) for b in range(nb)]
            else:
                nb = nbins
                d = (hi - lo) / nbins or 1.0
                bins = np.where(np.isnan(x), -1,
                                np.minimum((x - lo) / d, nbins - 1)
                                ).astype(np.int64)
                labels = [f"{lo + (b + 0.5) * d:5f}" for b in range(nb)]
        if has_na:  # NA bin leads, like `Tabulate.bin()`'s +_missing offset
            bins = bins + 1
            labels = ["missing(NA)"] + labels
            nb += 1
        return bins, labels, nb

    xb, xlabels, nx = axis(vx, nbins_predictor)
    yb, ylabels, ny = axis(vy, nbins_response)
    yraw = vy.to_numpy().astype(np.float64)

    counts = np.zeros((nx, ny))
    np.add.at(counts, (xb, yb), w)
    resp_w = np.zeros(nx)
    resp_sum = np.zeros(nx)
    ok = ~np.isnan(yraw)
    np.add.at(resp_w, xb[ok], w[ok])
    np.add.at(resp_sum, xb[ok], (w * yraw)[ok])
    with np.errstate(invalid="ignore", divide="ignore"):
        resp_mean = resp_sum / resp_w

    count_rows = [[xlabels[i], ylabels[j], float(counts[i, j])]
                  for i in range(nx) for j in range(ny)]
    count_table = TwoDimTable(
        f"(Weighted) co-occurrence counts of {predictor} vs {response}", "",
        [predictor, response, "counts"], ["string", "string", "double"],
        None, count_rows)
    resp_rows = [[xlabels[i],
                  None if resp_w[i] == 0 else float(resp_mean[i]),
                  float(resp_w[i])] for i in range(nx)]
    response_table = TwoDimTable(
        f"(Weighted) response means of {response} by {predictor}", "",
        [predictor, f"mean {response}", "counts"],
        ["string", "double", "double"], None, resp_rows)
    return count_table, response_table
