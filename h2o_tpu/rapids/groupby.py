"""GroupBy — `water/rapids/ast/prims/mungers/AstGroup` analog.

The reference hashes group keys into per-node maps then merges them across the
cluster. TPU-native: group keys are factorized into dense group ids (host pass
over the key columns — the categorical-interning analog), then EVERY aggregate
is one `jax.ops.segment_sum`-family reduction over the row-sharded data. All
aggregates for all columns run in one jitted program.

Supported aggs mirror AstGroup: nrow (count), sum, mean, min, max, sd/var,
sumSquares, mode (categorical); NA handling per-agg: "all" (NAs poison),
"rm" (drop), "ignore" (== rm for these aggs).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, T_INT, Vec

AGGS = ("nrow", "sum", "mean", "min", "max", "sd", "var", "sumSquares", "mode")


@partial(jax.jit, static_argnames=("ngroups",))
def _group_reduce(gid, inmask, cols, ngroups: int):
    """gid (R,), cols (R, C). Returns per-group {count, sum, sumsq, min, max,
    nacnt} for every column in one pass."""
    seg = partial(jax.ops.segment_sum, num_segments=ngroups)
    ok = ~jnp.isnan(cols) & inmask[:, None]
    x = jnp.where(ok, cols, 0.0)
    okf = ok.astype(jnp.float32)
    count = seg(okf, gid)
    nacnt = seg(jnp.isnan(cols).astype(jnp.float32)
                * inmask[:, None].astype(jnp.float32), gid)
    s = seg(x, gid)
    ss = seg(x * x, gid)
    mn = jax.ops.segment_min(jnp.where(ok, cols, jnp.inf), gid,
                             num_segments=ngroups)
    mx = jax.ops.segment_max(jnp.where(ok, cols, -jnp.inf), gid,
                             num_segments=ngroups)
    rows = seg(inmask.astype(jnp.float32), gid)
    return dict(count=count, nacnt=nacnt, sum=s, sumsq=ss, min=mn, max=mx,
                rows=rows)


def group_by(fr: Frame, by: list[str], aggs: list[tuple]) -> Frame:
    """aggs: [(op, col, na_handling), ...]; returns one row per group, sorted
    by group key (H2O returns groups sorted)."""
    # ---- factorize keys (host; the distributed-interning analog) ----------
    key_cols = [fr.vec(b).to_numpy() for b in by]
    n = fr.nrow
    # NA key sentinel: +inf (cannot collide with real data, unlike -1)
    keys = np.stack([np.where(np.isnan(c), np.inf, c) for c in key_cols], axis=1)
    uniq, inv = np.unique(keys, axis=0, return_inverse=True)
    ngroups = len(uniq)

    gid_padded = np.zeros(fr.vec(by[0]).plen, dtype=np.int32)
    gid_padded[:n] = inv
    inmask = np.zeros(fr.vec(by[0]).plen, dtype=bool)
    inmask[:n] = True

    # ---- one fused device reduction over all aggregated columns -----------
    value_cols = sorted({c for _, c, *_ in aggs if c})
    if value_cols:
        cols = jnp.stack([fr.vec(c).data for c in value_cols], axis=1)
    else:
        cols = jnp.zeros((len(gid_padded), 1), jnp.float32)
    stats = _group_reduce(jnp.asarray(gid_padded), jnp.asarray(inmask),
                          cols, ngroups)
    stats = {k: np.asarray(v) for k, v in stats.items()}
    colix = {c: i for i, c in enumerate(value_cols)}

    # ---- assemble output frame --------------------------------------------
    out_names, out_vecs = [], []
    for j, b in enumerate(by):
        v = fr.vec(b)
        vals = uniq[:, j].astype(np.float32)
        vals[np.isinf(uniq[:, j])] = np.nan
        out_names.append(b)
        out_vecs.append(Vec.from_numpy(vals, type=v.type, domain=v.domain))

    for spec in aggs:
        op, col, *rest = spec
        na = rest[0] if rest else "rm"
        if op == "nrow":
            out_names.append("nrow")
            out_vecs.append(Vec.from_numpy(stats["rows"].astype(np.float32),
                                           type=T_INT))
            continue
        i = colix[col]
        cnt = stats["count"][:, i]
        nac = stats["nacnt"][:, i]
        with np.errstate(invalid="ignore", divide="ignore"):
            if op == "sum":
                vals = stats["sum"][:, i]
            elif op == "sumSquares":
                vals = stats["sumsq"][:, i]
            elif op == "mean":
                vals = stats["sum"][:, i] / cnt
            elif op == "min":
                vals = np.where(cnt > 0, stats["min"][:, i], np.nan)
            elif op == "max":
                vals = np.where(cnt > 0, stats["max"][:, i], np.nan)
            elif op in ("sd", "var"):
                m = stats["sum"][:, i] / cnt
                var = np.maximum(stats["sumsq"][:, i] / cnt - m * m, 0.0)
                var = var * cnt / np.maximum(cnt - 1, 1)
                vals = np.sqrt(var) if op == "sd" else var
            elif op == "mode":
                vals = _group_mode(fr, col, inv, ngroups, n)
            else:
                raise ValueError(f"unknown agg {op!r}")
        if na == "all":
            vals = np.where(nac > 0, np.nan, vals)
        out_names.append(f"{op}_{col}")
        out_vecs.append(Vec.from_numpy(vals.astype(np.float32)))
    return Frame(out_names, out_vecs)


def _group_mode(fr: Frame, col: str, inv: np.ndarray, ngroups: int, n: int):
    host = fr.vec(col).to_numpy()[:n]
    out = np.full(ngroups, np.nan, dtype=np.float32)
    ok = ~np.isnan(host)
    for g in range(ngroups):
        vals = host[(inv == g) & ok].astype(np.int64)
        if vals.size:
            out[g] = np.bincount(vals).argmax()
    return out
