"""Rapids — frame algebra. TPU-native analog of `water/rapids/` (24,566 LoC).

The reference evaluates client-submitted Lisp ASTs (`Rapids.exec`,
`water/rapids/Rapids.java:60,86`) over ~200 primitive ops. Here the same
operations are plain Python functions over device-resident Vecs/Frames —
the lazy-AST layer exists client-side in h2o-py only because every op was a
REST round-trip; in-process there is nothing to batch (deliberate divergence,
SURVEY.md §7 "client compatibility").
"""

from .ops import (binop, cumulative, hist, ifelse, reduce_op, round_digits,
                  signif, table, time_part, unique, unop)
from .groupby import group_by
from .merge import merge, sort
from . import strings

__all__ = [
    "binop", "unop", "reduce_op", "cumulative", "ifelse", "table", "unique",
    "hist", "round_digits", "signif", "time_part", "group_by", "merge",
    "sort", "strings",
]
