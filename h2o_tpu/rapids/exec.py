"""Rapids.exec — the Lisp-ish expression evaluator behind `/99/Rapids`.

Analog of `water/rapids/Rapids.java:60,86` (tokenizer/parser) +
`water/rapids/ast/AstExec.java` (apply) + `water/rapids/Session.java`
(ref-counted result tracking). Clients submit strings like

    (tmp= py_1 (cols_py higgs [0 3]))
    (mean (cols frame_key 'x') true)
    (:= fr (* (cols fr 'x') 2) 1 [])

The grammar (`Rapids.java` class comment): ``( )`` applies a primitive;
``[ ]`` is a number/string list; numbers, ``'str'``/``"str"`` strings, ids
reference env/DKV objects; ``tmp=``/``:=`` assign.

Primitives dispatch onto the device-side rapids ops (ops/groupby/merge/
strings) — the evaluator is a thin host-side shim; all bulk work stays
sharded on the mesh. The prim set covers what h2o-py's expr layer actually
emits for core munging (SURVEY.md §7 scoping note).
"""

from __future__ import annotations

import numpy as np

from ..backend.kvstore import STORE
from ..frame.frame import Frame
from ..frame.vec import T_CAT, Vec
from . import advmath
from . import mungers
from . import strings as strmod
from .groupby import group_by
from .merge import merge as merge_fn, sort as sort_fn
from .ops import (binop, cumulative, ifelse, reduce_op, round_digits, signif,
                  table, time_part, unique, unop)


# ---------------------------------------------------------------------------
# session (`water/rapids/Session.java`)
# ---------------------------------------------------------------------------
class Session:
    """Holds temp results (`tmp=`) between Rapids calls; `end()` sweeps."""

    def __init__(self, session_id: str | None = None):
        self.id = session_id or f"session_{np.random.randint(1 << 30)}"
        self.temps: dict[str, object] = {}

    def lookup(self, name: str):
        if name in self.temps:
            return self.temps[name]
        return STORE.get(name)

    def assign(self, name: str, value):
        if isinstance(value, Vec):
            # a keyed temp is always frame-shaped (the reference's tmp= puts
            # a Frame in DKV even for single-Vec expression results)
            value = _as_frame(value)
        self.temps[name] = value
        if isinstance(value, Frame):
            value.key = name
            STORE.put(name, value)
        return value

    def end(self):
        for k in self.temps:
            STORE.remove(k, cascade=False)
        self.temps.clear()


# ---------------------------------------------------------------------------
# tokenizer / parser (`Rapids.java:86` parse)
# ---------------------------------------------------------------------------
class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self):
        c = self.peek()
        if c == "(":
            return self._list(")", "exec")
        if c == "[":
            return self._list("]", "list")
        if c == "{":
            return self._list("}", "fun")
        if c in "'\"":
            return self._string(c)
        return self._token()

    def _list(self, close, kind):
        self.i += 1  # consume open
        items = []
        while self.peek() != close:
            if self.peek() == "":
                raise ValueError(f"unbalanced rapids expression: {self.s}")
            items.append(self.parse())
        self.i += 1  # consume close
        return (kind, items)

    def _string(self, q):
        self.i += 1
        out = []
        while self.i < len(self.s) and self.s[self.i] != q:
            if self.s[self.i] == "\\":
                self.i += 1
            out.append(self.s[self.i])
            self.i += 1
        self.i += 1
        return ("str", "".join(out))

    def _token(self):
        j = self.i
        while j < len(self.s) and not self.s[j].isspace() and self.s[j] not in "()[]{}":
            j += 1
        tok, self.i = self.s[self.i:j], j
        if not tok:
            raise ValueError(f"parse error at {self.i} in {self.s!r}")
        try:
            return ("num", float(tok))
        except ValueError:
            pass
        if ":" in tok and tok not in (":=",):  # 0:10 span
            lo, _, hi = tok.partition(":")
            try:
                return ("span", (int(lo), int(hi)))
            except ValueError:
                pass
        return ("id", tok)


# ---------------------------------------------------------------------------
# evaluator
# ---------------------------------------------------------------------------
def _as_vec(x, nrow=None):
    if isinstance(x, Frame):
        if x.ncol != 1:
            raise ValueError("expected a single-column frame")
        return x.vec(0)
    return x


def _as_frame(x) -> Frame:
    if isinstance(x, Vec):
        return Frame([x.key or "C1"], [x])
    if not isinstance(x, Frame):
        raise ValueError(f"expected frame, got {type(x).__name__}")
    return x


def _col_indices(fr: Frame, sel) -> list[int]:
    if isinstance(sel, float):
        return [int(sel)]
    if isinstance(sel, str):
        return [fr.names.index(sel)]
    if isinstance(sel, tuple) and len(sel) == 2:  # span
        return list(range(sel[0], sel[1]))
    if isinstance(sel, list):
        out = []
        for s in sel:
            out.extend(_col_indices(fr, s))
        return out
    raise ValueError(f"bad column selector {sel!r}")


def _row_mask(fr: Frame, sel) -> np.ndarray | None:
    """None = all rows; else bool mask or index list."""
    if isinstance(sel, list) and not sel:
        return None
    if isinstance(sel, Frame):
        sel = _as_vec(sel)
    if isinstance(sel, Vec):
        m = sel.to_numpy()
        if set(np.unique(m[~np.isnan(m)])) <= {0.0, 1.0}:
            return ~np.isnan(m) & (m == 1.0)
        return m[~np.isnan(m)].astype(np.int64)
    if isinstance(sel, float):
        return np.asarray([int(sel)])
    if isinstance(sel, tuple):
        return np.arange(sel[0], sel[1])
    if isinstance(sel, list):
        out: list = []
        for _x in sel:
            if isinstance(_x, tuple):  # [a:b] span inside a list
                out.extend(range(_x[0], _x[1]))
            else:
                out.append(int(_x))
        return np.asarray(out, dtype=np.int64)
    return None


def _subset_rows(fr: Frame, rows) -> Frame:
    if rows is None:
        return fr
    idx = np.where(rows)[0] if rows.dtype == bool else rows
    oob = (idx < 0) | (idx >= fr.nrow)
    if not oob.any():
        return fr.take(idx)
    # h2o semantics: selecting past the last row yields NA rows, not an
    # error (`AstRows` reads beyond the Vec as NA)
    out = fr.take(np.clip(idx, 0, max(fr.nrow - 1, 0)))
    from ..frame.vec import Vec as _Vec

    for name in list(out.names):
        v = out.vec(name)
        if v.is_string():
            hd = v.host_data.copy()
            hd[oob] = None
            out.replace(name, _Vec(None, len(idx), type=v.type,
                                   host_data=hd))
        else:
            x = v.to_numpy().astype(np.float64)
            x[oob] = np.nan
            out.replace(name, _Vec.from_numpy(x, type=v.type,
                                              domain=v.domain))
    return out


class Rapids:
    """Evaluator instance bound to a Session."""

    def __init__(self, session: Session | None = None):
        self.session = session or Session()
        self._scopes: list[dict] = []  # lambda parameter bindings

    # -- public entry (`Rapids.exec`) ----------------------------------------
    def exec(self, expr: str):
        ast = _Parser(expr).parse()
        return self._eval(ast)

    # -- eval ----------------------------------------------------------------
    def _eval(self, node):
        kind, val = node
        if kind == "num":
            return val
        if kind == "str":
            return val
        if kind == "span":
            return val
        if kind == "list":
            return [self._eval(x) for x in val]
        if kind == "fun":
            # { id1 id2 . body } — `water/rapids/ast/AstFunction.java`
            params, body, seen_dot = [], None, False
            for item in val:
                if item == ("id", "."):
                    seen_dot = True
                elif not seen_dot:
                    params.append(item[1])
                else:
                    body = item
            if body is None:
                raise ValueError("lambda without body: { ids . expr }")
            return RLambda(self, params, body)
        if kind == "id":
            lit = {"true": 1.0, "TRUE": 1.0, "True": 1.0,
                   "false": 0.0, "FALSE": 0.0, "False": 0.0,
                   "NA": float("nan"), "NaN": float("nan"),
                   "null": None, "None": None,
                   "_": None}  # h2o-py placeholder for defaulted args
            if val in lit:
                return lit[val]
            for scope in reversed(self._scopes):
                if val in scope:
                    return scope[val]
            obj = self.session.lookup(val)
            if obj is None:
                raise KeyError(f"rapids: unknown id '{val}'")
            return obj
        if kind == "exec":
            if not val:
                raise ValueError("empty () application")
            opkind, opname = val[0]
            if opkind != "id":
                raise ValueError(f"cannot apply {val[0]!r}")
            return self._apply(opname, val[1:])
        raise ValueError(f"bad ast node {node!r}")

    def _apply(self, op, raw_args):
        # assignment forms keep their first arg un-evaluated (a fresh name)
        if op in ("tmp=", "assign"):
            name = raw_args[0][1]
            value = self._eval(raw_args[1])
            return self.session.assign(name, value)
        if op == "rm":
            name = raw_args[0][1]
            self.session.temps.pop(name, None)
            STORE.remove(name, cascade=False)
            return None
        args = [self._eval(a) for a in raw_args]
        fn = _PRIMS.get(op)
        if fn is None:
            raise ValueError(f"rapids: unimplemented primitive '{op}'")
        return fn(self, *args)


class RLambda:
    """A parsed `{ ids . body }` function value (`AstFunction.java`)."""

    def __init__(self, rapids: "Rapids", params: list[str], body):
        self.rapids = rapids
        self.params = params
        self.body = body

    def __call__(self, *vals):
        self.rapids._scopes.append(dict(zip(self.params, vals)))
        try:
            return self.rapids._eval(self.body)
        finally:
            self.rapids._scopes.pop()


# row-wise vectorized fast path for `apply` margin=1 lambdas of the form
# { x . (op x [na_rm]) } — one fused reduction instead of a per-row loop.
# Keyed (op, na_rm) so NA semantics match _prim_reduce exactly: na_rm=False
# (the reducer default) propagates NaN through the row.
_ROW_REDUCERS = {
    ("mean", True): lambda M: np.nanmean(M, axis=1),
    ("mean", False): lambda M: np.mean(M, axis=1),
    ("sum", True): lambda M: np.nansum(M, axis=1),
    ("sum", False): lambda M: np.sum(M, axis=1),
    ("min", True): lambda M: np.nanmin(M, axis=1),
    ("min", False): lambda M: np.min(M, axis=1),
    ("max", True): lambda M: np.nanmax(M, axis=1),
    ("max", False): lambda M: np.max(M, axis=1),
    ("median", True): lambda M: np.nanmedian(M, axis=1),
    ("median", False): lambda M: np.median(M, axis=1),
    ("sd", True): lambda M: np.nanstd(M, axis=1, ddof=1),
    ("sd", False): lambda M: np.std(M, axis=1, ddof=1),
    ("var", True): lambda M: np.nanvar(M, axis=1, ddof=1),
    ("var", False): lambda M: np.var(M, axis=1, ddof=1),
}

_NA_RM_LITERALS = {("id", "true"): True, ("id", "TRUE"): True,
                   ("id", "True"): True, ("num", 1.0): True,
                   ("id", "false"): False, ("id", "FALSE"): False,
                   ("id", "False"): False, ("num", 0.0): False}


def _apply(R, fr, margin, fun):
    """(apply fr margin fun) — `AstApply.java`: 1 = rows, 2 = columns."""
    fr = _as_frame(fr)
    margin = int(margin)
    if not isinstance(fun, RLambda):
        raise ValueError("apply expects a function {x . body}")
    if margin == 2:
        results = [fun(Frame([n], [fr.vec(n)])) for n in fr.names]
        if all(isinstance(r, (int, float)) for r in results):
            return Frame(fr.names, [Vec.from_numpy(np.asarray([r]))
                                    for r in results])
        cols = []
        for n, r in zip(fr.names, results):
            v = _as_vec(r) if isinstance(r, (Frame, Vec)) else Vec.from_numpy(
                np.asarray([float(r)]))
            cols.append(v)
        nr = max(v.nrow for v in cols)
        cols = [v if v.nrow == nr else Vec.from_numpy(
            np.resize(v.to_numpy(), nr)) for v in cols]
        return Frame(list(fr.names), cols)
    if margin != 1:
        raise ValueError("apply margin must be 1 (rows) or 2 (cols)")
    body = fun.body
    if (body[0] == "exec" and len(body[1]) in (2, 3)
            and body[1][1] == ("id", fun.params[0])
            and (len(body[1]) == 2 or body[1][2] in _NA_RM_LITERALS)):
        na_rm = (_NA_RM_LITERALS[body[1][2]] if len(body[1]) == 3
                 else False)  # _prim_reduce's na_rm default
        red = _ROW_REDUCERS.get((body[1][0][1], na_rm))
        if red is not None:
            M = np.asarray(fr.as_matrix())[: fr.nrow]
            return Frame(["apply"], [Vec.from_numpy(red(M))])
    # general path: per-row evaluation (host loop; reference runs an MRTask).
    # The row binds as a single column of its values (ValRow semantics: row
    # reducers fold across the row's cells).
    M = np.asarray(fr.as_matrix())[: fr.nrow]
    rows = []
    for i in range(fr.nrow):
        r = fun(Frame(["row"], [Vec.from_numpy(M[i, :])]))
        if isinstance(r, (Frame, Vec)):
            r = [float(x) for x in _as_vec(r).to_numpy()]
        rows.append(r if isinstance(r, list) else [float(r)])
    width = max(len(r) for r in rows)
    out = np.full((fr.nrow, width), np.nan)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return Frame([f"C{j + 1}" for j in range(width)] if width > 1
                 else ["apply"],
                 [Vec.from_numpy(out[:, j]) for j in range(width)])


def _ddply(R, fr, group_cols, fun):
    """(ddply fr [cols] fun) — per-group lambda results (`AstDdply.java`)."""
    fr = _as_frame(fr)
    if not isinstance(fun, RLambda):
        raise ValueError("ddply expects a function {x . body}")
    gidx = _col_indices(fr, group_cols)
    keys = [fr.vec(i).to_numpy() for i in gidx]
    tags = {}
    for r in range(fr.nrow):
        t = tuple(np.nan if np.isnan(k[r]) else float(k[r]) for k in keys)
        tags.setdefault(t, []).append(r)
    grows, rrows = [], []
    for t, idx in sorted(tags.items(),
                         key=lambda kv: tuple(
                             (np.inf if x != x else x) for x in kv[0])):
        sub = fr.take(np.asarray(idx))
        r = fun(sub)
        if isinstance(r, (Frame, Vec)):
            r = [float(x) for x in _as_vec(r).to_numpy()]
        grows.append(list(t))
        rrows.append(r if isinstance(r, list) else [float(r)])
    width = max(len(r) for r in rrows) if rrows else 1
    names = [fr.names[i] for i in gidx] + [f"ddply_C{j + 1}"
                                           for j in range(width)]
    cols = []
    for j in range(len(gidx)):
        src = fr.vec(gidx[j])
        cols.append(Vec.from_numpy(
            np.asarray([g[j] for g in grows], dtype=np.float32),
            type=src.type, domain=src.domain))
    for j in range(width):
        cols.append(Vec.from_numpy(np.asarray(
            [r[j] if j < len(r) else np.nan for r in rrows])))
    return Frame(names, cols)


def _append_prim(R, dst, *rest):
    """(append dst (src name)+ ) — `AstAppend.java`."""
    out = _as_frame(dst)
    if len(rest) % 2:
        raise ValueError("append needs (src, name) pairs")
    for i in range(0, len(rest), 2):
        out = mungers.append(out, rest[i], str(rest[i + 1]))
    return out


def _rect_assign_prim(R, dst, src, cols, rows=None):
    """(:= dst src col_expr row_expr) — `AstRectangleAssign.java`."""
    fr = _as_frame(dst)
    cidx = _col_indices(fr, cols) if cols not in ([],) else []
    if not cidx:  # "empty really means all"
        cidx = list(range(fr.ncol))
    return mungers.rectangle_assign(fr, src, cidx, _row_mask(fr, rows))


def _merge_prim(R, l, r, all_l=False, all_r=False, by_l=None, by_r=None,
                method="auto"):
    """(merge l r all_x all_y [bx] [by] method) — `AstMerge.java`. Explicit
    by-columns come as index lists; differently-named right keys are
    realigned onto the left names before the join."""
    lf, rf = _as_frame(l), _as_frame(r)
    bx = _col_indices(lf, by_l) if by_l not in (None, []) else None
    by_ = _col_indices(rf, by_r) if by_r not in (None, []) else None
    by_names = None
    if bx:
        by_names = [lf.names[i] for i in bx]
        if by_:
            if len(by_) != len(bx):
                raise ValueError("merge: by_x and by_y lengths differ")
            rnames = list(rf.names)
            for li, ri in zip(bx, by_):
                rnames[ri] = lf.names[li]
            rf = Frame(rnames, list(rf.vecs))
    return merge_fn(lf, rf, by=by_names,
                    all_x=bool(all_l), all_y=bool(all_r))


def _rename_key(R, old: str, new: str):
    """(rename "old" "new") — rename a DKV key (`AstRename.java`)."""
    obj = R.session.lookup(old)
    if obj is None:
        raise KeyError(f"rename: no such key '{old}'")
    R.session.temps.pop(old, None)
    STORE.remove(old, cascade=False)
    obj.key = new
    STORE.put(new, obj)
    return float("nan")


def _sumaxis(fr: Frame, na_rm: bool, axis: int):
    """(sumaxis fr na_rm axis) — per-column (0) or per-row (1) sums."""
    M = np.asarray(fr.as_matrix())[: fr.nrow]
    red = np.nansum if na_rm else np.sum
    if axis == 1:
        return Frame(["sum"], [Vec.from_numpy(red(M, axis=1))])
    return Frame(list(fr.names),
                 [Vec.from_numpy(np.asarray([red(M[:, j])]))
                  for j in range(fr.ncol)])


# ---------------------------------------------------------------------------
# primitive table (`water/rapids/ast/prims/**` subset)
# ---------------------------------------------------------------------------
# numpy ufuncs so scalar edge cases match the vector path: (/ 1 0) → inf,
# (%% x 0) → nan, (^ -1 0.5) → nan — never a Python ZeroDivisionError
_SCALAR_BINOPS = {
    "+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide,
    "^": np.float_power, "%%": np.fmod,  # Java %: sign follows dividend
    # operands truncate BEFORE the divide (AstIntDiv: `(int) l / (int) r`)
    "intDiv": lambda a, b: np.where(np.trunc(b) == 0, np.nan,
                                    np.trunc(np.trunc(a) / np.trunc(b))),
    "%/%": lambda a, b: np.where(b == 0, np.nan, np.trunc(np.divide(a, b))),
    "==": np.equal, "!=": np.not_equal,
    "<": np.less, "<=": np.less_equal,
    ">": np.greater, ">=": np.greater_equal,
    "&": lambda a, b: (a != 0) & (b != 0),
    "|": lambda a, b: (a != 0) | (b != 0),
    "&&": lambda a, b: (a != 0) & (b != 0),
    "||": lambda a, b: (a != 0) | (b != 0),
}


def _prim_binop(op):
    def fn(R, l, r):
        if isinstance(l, (int, float)) and isinstance(r, (int, float)):
            with np.errstate(divide="ignore", invalid="ignore"):
                return float(_SCALAR_BINOPS[op](np.float64(l),
                                                np.float64(r)))
        # multi-column frames apply column-wise (`AstBinOp.frame_op_frame`)
        lm = isinstance(l, Frame) and l.ncol > 1
        rm = isinstance(r, Frame) and r.ncol > 1
        if lm or rm:
            n = l.ncol if lm else r.ncol
            if lm and rm and r.ncol != n:
                raise ValueError(
                    f"binop '{op}': frames have {l.ncol} vs {r.ncol} columns")
            vecs = [binop(op,
                          l.vec(i) if lm else _as_vec(l)
                          if isinstance(l, (Frame, Vec)) else l,
                          r.vec(i) if rm else _as_vec(r)
                          if isinstance(r, (Frame, Vec)) else r)
                    for i in range(n)]
            return Frame(list((l if lm else r).names), vecs)
        return binop(op, _as_vec(l), _as_vec(r))
    return fn


def _prim_unop(op, rename=None):
    """``rename``: per-column output naming (AstIsNa's "isNA(col)")."""
    def fn(R, v):
        if isinstance(v, Frame) and v.ncol > 1:
            names = [rename(n) if rename else n for n in v.names]
            return Frame(names,
                         [unop(op, v.vec(i)) for i in range(v.ncol)])
        out = unop(op, _as_vec(v))
        if rename and isinstance(v, Frame):
            return Frame([rename(v.names[0])], [out])
        return out
    return fn


def _prim_reduce(op):
    def fn(R, v, na_rm=False):
        fr = _as_frame(v)
        vals = [reduce_op(op, fr.vec(i), na_rm=bool(na_rm))
                for i in range(fr.ncol)]
        return vals[0] if len(vals) == 1 else vals
    return fn


def _cols(R, fr, sel):
    fr = _as_frame(fr)
    idx = _col_indices(fr, sel)
    return fr.subframe([fr.names[i] for i in idx])


def _rows(R, fr, sel):
    return _subset_rows(_as_frame(fr), _row_mask(_as_frame(fr), sel))


def _cbind(R, *frs):
    names, vecs = [], []
    for f in frs:
        f = _as_frame(f)
        for n in f.names:
            nm, k = n, 1
            while nm in names:
                nm, k = f"{n}{k}", k + 1
            names.append(nm)
            vecs.append(f.vec(n))
    return Frame(names, vecs)


def _rbind(R, *frs):
    frs = [_as_frame(f) for f in frs]
    return frs[0].concat_rows(*frs[1:])


def _colnames(R, fr, idxs, names):
    fr = _as_frame(fr)
    idx = _col_indices(fr, idxs)
    new = names if isinstance(names, list) else [names]
    out = Frame(fr.names, fr.vecs)
    for i, nm in zip(idx, new):
        out._names[i] = str(nm)
    return out


def _group_by(R, fr, by, *aggspec):
    fr = _as_frame(fr)
    by_names = [fr.names[i] for i in _col_indices(fr, list(by))]
    aggs = []
    for i in range(0, len(aggspec), 3):
        agg, col, na = aggspec[i], aggspec[i + 1], aggspec[i + 2]
        col_name = fr.names[_col_indices(fr, col)[0]]
        aggs.append((agg, col_name, na))
    return group_by(fr, by_names, aggs)


def _w2v_to_frame(m) -> Frame:
    """`water/rapids/ast/prims/models/AstWord2VecToFrame` — dump a word2vec
    model's learned embeddings as a frame of [Word, V1..VD]."""
    import numpy as np

    from ..frame.vec import T_STR, Vec

    words = sorted(m.vocab, key=m.vocab.get)
    W = np.asarray(m.vectors)
    vecs = [Vec(None, len(words), type=T_STR,
                host_data=np.array(words, dtype=object))]
    names = ["Word"] + [f"V{j + 1}" for j in range(W.shape[1])]
    for j in range(W.shape[1]):
        vecs.append(Vec.from_numpy(W[[m.vocab[w] for w in words], j]
                                   .astype(np.float32)))
    return Frame(names, vecs)


def _resolve_model(obj):
    m = STORE.get(obj) if isinstance(obj, str) else obj
    if m is None:
        raise KeyError(f"rapids: unknown model '{obj}'")
    return m


def _reset_threshold_prim(R, model, threshold):
    """`AstModelResetThreshold`: swap the binomial decision threshold used
    for the predict label, returning the old one."""
    m = _resolve_model(model)
    old = float(getattr(m, "default_threshold", 0.5))
    m.default_threshold = float(threshold)
    return old


def _table_to_frame(t) -> Frame:
    cols = list(zip(*t.cell_values)) if t.cell_values else [
        () for _ in t.col_header]
    vecs, names = [], []
    for name, ctype, col in zip(t.col_header, t.col_types, cols):
        names.append(name)
        if ctype in ("string",):
            vecs.append(Vec(None, len(col), type="string",
                            host_data=np.asarray(col, dtype=object)))
        else:
            vecs.append(Vec.from_numpy(np.asarray(
                [np.nan if v is None else float(v) for v in col],
                np.float32)))
    return Frame(names, vecs)


def _permutation_varimp_prim(R, model, fr, metric="AUTO", n_repeats=1,
                             seed=-1):
    """`AstPermutationVarImp` role: the PVI table as a frame."""
    m = _resolve_model(model)
    t = m.permutation_importance(_as_frame(fr), metric=str(metric),
                                 n_repeats=int(n_repeats), seed=int(seed))
    return _table_to_frame(t)


def _make_leaderboard_prim(R, obj, lb_frame=None, sort_metric=None, *rest):
    """`AstMakeLeaderboard` role: leaderboard frame from an AutoML run (by
    key) or an explicit list of model keys, optionally re-scored on a
    leaderboard frame and sorted by a named metric."""
    from ..models.automl import H2OAutoML, Leaderboard

    if isinstance(obj, str) and isinstance(STORE.get(obj), H2OAutoML):
        return STORE.get(obj).leaderboard.as_frame()
    keys = obj if isinstance(obj, list) else [obj]
    if not keys:
        raise ValueError("makeLeaderboard: no models given")
    models = [_resolve_model(k) for k in keys]
    sm = (str(sort_metric) if sort_metric not in (None, "", "AUTO", "auto")
          else None)
    overrides: dict = {}
    if lb_frame not in (None, ""):
        # rank on metrics recomputed against the supplied frame, without
        # mutating the models' stored metrics
        fr = _as_frame(lb_frame if not isinstance(lb_frame, str)
                       else STORE.get(lb_frame))
        overrides = {m.key: m.model_performance(fr) for m in models}

    class _FrameScoredLB(Leaderboard):
        def _metric(self, m, name):
            mm = overrides.get(m.key)
            if mm is None:
                return super()._metric(m, name)
            v = getattr(mm, name, None)
            return (None if v is None
                    or (isinstance(v, float) and np.isnan(v)) else v)

    lb = _FrameScoredLB(models[0].output.model_category, sm)
    for m in models:
        lb.add(m)
    return lb.as_frame()


_PRIMS = {
    # math / comparison
    **{op: _prim_binop(op) for op in
       ("+", "-", "*", "/", "^", "%%", "intDiv", "==", "!=", "<", "<=", ">",
        ">=", "&", "|", "&&", "||")},
    **{op: _prim_unop(op) for op in
       ("abs", "ceiling", "floor", "trunc", "exp", "expm1", "log", "log10",
        "log2", "log1p", "sqrt", "sin", "cos", "tan", "asin", "acos", "atan",
        "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "sign", "not",
        "gamma", "lgamma", "digamma", "trigamma", "cospi", "sinpi", "tanpi")},
    "is.na": _prim_unop("isna", rename=lambda n: f"isNA({n})"),
    **{op: _prim_reduce(op) for op in
       ("min", "max", "sum", "mean", "median", "sd", "var", "prod", "all",
        "any")},
    **{op: (lambda o: (lambda R, v: cumulative(o, _as_vec(v))))(op)
       for op in ("cumsum", "cumprod", "cummin", "cummax")},
    "round": lambda R, v, d=0: round_digits(_as_vec(v), int(d)),
    "signif": lambda R, v, d=6: signif(_as_vec(v), int(d)),
    "ifelse": lambda R, t, y, n: ifelse(_as_vec(t), _as_vec(y, 0) if isinstance(y, (Vec, Frame)) else y,
                                        _as_vec(n, 0) if isinstance(n, (Vec, Frame)) else n),
    "table": lambda R, v: table(_as_vec(v)),
    "unique": lambda R, v: unique(_as_vec(v)),
    # munging
    "cols": _cols, "cols_py": _cols,
    "rows": _rows,
    "cbind": _cbind,
    "rbind": _rbind,
    "colnames=": _colnames,
    "nrow": lambda R, fr: float(_as_frame(fr).nrow),
    "ncol": lambda R, fr: float(_as_frame(fr).ncol),
    "is.factor": lambda R, v: float(_as_vec(v).is_categorical()),
    "as.factor": lambda R, v: _asfactor(_as_vec(v)),
    "as.numeric": lambda R, v: _asnumeric(_as_vec(v)),
    "GB": _group_by,
    "merge": _merge_prim,
    "sort": lambda R, fr, by, asc=None: sort_fn(
        _as_frame(fr),
        [_as_frame(fr).names[i] for i in _col_indices(_as_frame(fr), by)],
        None if asc is None else [bool(a) for a in (asc if isinstance(asc, list) else [asc])]),
    # strings
    "toupper": lambda R, v: strmod.toupper(_as_vec(v)),
    "tolower": lambda R, v: strmod.tolower(_as_vec(v)),
    "trim": lambda R, v: strmod.trim(_as_vec(v)),
    "nchar": lambda R, v: strmod.nchar(_as_vec(v)),
    "sub": lambda R, pat, rep, v, ic=False: strmod.sub(_as_vec(v), pat, rep, ignore_case=bool(ic)),
    "gsub": lambda R, pat, rep, v, ic=False: strmod.gsub(_as_vec(v), pat, rep, ignore_case=bool(ic)),
    "grep": lambda R, v, pat, ic=False, inv=False, ol=True: strmod.grep(
        _as_vec(v), pat, ignore_case=bool(ic), invert=bool(inv),
        output_logical=bool(ol)),
    "lstrip": lambda R, v, set=None: strmod.lstrip(_as_vec(v), set),
    "rstrip": lambda R, v, set=None: strmod.rstrip(_as_vec(v), set),
    "substring": lambda R, v, s, e=None: strmod.substring(
        _as_vec(v), int(s), None if e is None else int(e)),
    "replacefirst": lambda R, v, pat, rep, ic=False: strmod.sub(
        _as_vec(v), pat, rep, ignore_case=bool(ic)),
    "replaceall": lambda R, v, pat, rep, ic=False: strmod.gsub(
        _as_vec(v), pat, rep, ignore_case=bool(ic)),
    "countmatches": lambda R, v, pats: strmod.countmatches(_as_vec(v), pats),
    "strsplit": lambda R, v, pat: (lambda vs: Frame(
        [f"C{i + 1}" for i in range(len(vs))], vs))(
            strmod.strsplit(_as_vec(v), pat)),
    "entropy": lambda R, v: strmod.entropy(_as_vec(v)),
    "strDistance": lambda R, a, b, measure="lv", ce=True: strmod.strdistance(
        _as_vec(a), _as_vec(b), measure, bool(ce)),
    "tokenize": lambda R, v, split=" ": strmod.tokenize(_as_vec(v), split),
    "ascharacter": lambda R, v: strmod.ascharacter(_as_vec(v)),
    # time
    **{part: (lambda p: (lambda R, v: time_part(_as_vec(v), p)))(part)
       for part in ("year", "month", "day", "dayOfWeek", "hour", "minute",
                    "second", "millis")},
    "moment": lambda R, *a: advmath.moment(*a),
    "mktime": lambda R, *a: advmath.moment(*a),
    # advmath / munging (second wave, `prims/{advmath,mungers,matrix}`)
    "skewness": lambda R, v, na_rm=True: advmath.skewness(_as_vec(v)),
    "kurtosis": lambda R, v, na_rm=True: advmath.kurtosis(_as_vec(v)),
    "cor": lambda R, x, y, use="everything", method="Pearson":
        advmath.cor(_as_frame(x), _as_frame(y), use, method),
    "quantile": lambda R, fr, probs, interp="interpolate", w="_":
        advmath.quantile_frame(_as_frame(fr), probs, interp),
    "h2o.impute": lambda R, fr, col=-1, method="mean", combine="interpolate",
        by=None, gbframe=None, values=None:
        advmath.impute(_as_frame(fr), None if col is None else int(col),
                       method, combine, by, values),
    "scale": lambda R, fr, center=True, scale=True:
        advmath.scale_frame(_as_frame(fr), _maybe_list(center),
                            _maybe_list(scale)),
    "na.omit": lambda R, fr: advmath.na_omit(_as_frame(fr)),
    "h2o.fillna": lambda R, fr, method="forward", axis=0, maxlen=1:
        advmath.fillna(_as_frame(fr), method, int(axis), int(maxlen)),
    "which": lambda R, v: advmath.which(_as_vec(v)),
    "which.max": lambda R, fr, na_rm=True, axis=0:
        advmath.which_extreme(_as_frame(fr), bool(na_rm), int(axis), mx=True),
    "which.min": lambda R, fr, na_rm=True, axis=0:
        advmath.which_extreme(_as_frame(fr), bool(na_rm), int(axis), mx=False),
    "match": lambda R, v, table, nomatch=None, start=1.0:
        advmath.match(_as_vec(v), table, nomatch, float(start)),
    "cut": lambda R, v, breaks, labels=None, il=False, right=True, dig=3:
        advmath.cut(_as_vec(v), breaks, labels, bool(il), bool(right),
                    int(dig)),
    "difflag1": lambda R, v: advmath.difflag1(_as_vec(v)),
    "kfold_column": lambda R, v, n, seed=-1:
        advmath.kfold_column(_as_vec(v), int(n), seed),
    "stratified_kfold_column": lambda R, v, n, seed=-1:
        advmath.stratified_kfold_column(_as_vec(v), int(n), seed),
    "h2o.random_stratified_split": lambda R, v, frac=0.2, seed=-1:
        advmath.stratified_split(_as_vec(v), float(frac), seed),
    "levels": lambda R, fr: advmath.levels(_as_frame(fr)),
    "relevel": lambda R, v, lvl: advmath.relevel(_as_vec(v), str(lvl)),
    "setDomain": lambda R, v, *a: advmath.set_domain(_as_vec(v), a[-1]),
    "pivot": lambda R, fr, index, column, value:
        advmath.pivot(_as_frame(fr), index, column, value),
    "melt": lambda R, fr, ids, vals=None, var="variable", val="value",
        skipna=False: advmath.melt(_as_frame(fr), ids, vals, var, val,
                                   bool(skipna)),
    "t": lambda R, fr: advmath.transpose(_as_frame(fr)),
    "x*y": lambda R, x, y: advmath.mmult(_as_frame(x), _as_frame(y)),
    "rank_within_groupby": lambda R, fr, g, s, asc=None,
        name="New_Rank_column", *rest: advmath.rank_within_group_by(
            _as_frame(fr), g, s, asc, str(name)),
    "topn": lambda R, fr, col, pct, bottom=0.0:
        advmath.topn(_as_frame(fr), int(col), float(pct), bool(bottom)),
    "interaction": lambda R, fr, factors, pairwise=False, mf=100, mo=1:
        advmath.interaction(_as_frame(fr), factors, bool(pairwise), int(mf),
                            int(mo)),
    # third wave: mutation / repeaters / mungers (`prims/{assign,repeaters,
    # mungers,filters,timeseries}`)
    "append": _append_prim,
    ":=": _rect_assign_prim,
    "seq": lambda R, frm, to, by=1.0: mungers.seq(float(frm), float(to),
                                                  float(by)),
    "seq_len": lambda R, n: mungers.seq_len(n),
    "rep_len": lambda R, x, n: mungers.rep_len(x, n),
    "mode": lambda R, v: mungers.mode(_as_vec(v)),
    "distance": lambda R, x, y, measure="l2": mungers.distance(
        _as_frame(x), _as_frame(y), str(measure)),
    "hist": lambda R, v, breaks="sturges": mungers.hist(_as_vec(v), breaks),
    "modulo_kfold_column": lambda R, v, n: mungers.modulo_kfold_column(
        _as_vec(v), int(n)),
    "dropdup": lambda R, fr, cols, keep="first": mungers.dropdup(
        _as_frame(fr), cols, str(keep)),
    "h2o.mad": lambda R, fr, combine="interpolate", const=1.4826:
        mungers.mad(_as_frame(fr), str(combine), float(const)),
    "perfectAUC": lambda R, p, y: mungers.perfect_auc(_as_vec(p), _as_vec(y)),
    "nlevels": lambda R, v: mungers.nlevels(_as_vec(v)),
    "any.factor": lambda R, fr: mungers.any_factor(_as_frame(fr)),
    "is.character": lambda R, v: float(_as_vec(v).is_string()),
    "is.numeric": lambda R, v: float(_as_vec(v).is_numeric()
                                     and not _as_vec(v).is_categorical()),
    "columnsByType": lambda R, fr, t="numeric": mungers.columns_by_type(
        _as_frame(fr), str(t)),
    "rename": lambda R, old, new: _rename_key(R, str(old), str(new)),
    "setLevel": lambda R, v, lvl: mungers.set_level(_as_vec(v), str(lvl)),
    "appendLevels": lambda R, v, lvls: mungers.append_levels(_as_vec(v), lvls),
    "relevel.by.freq": lambda R, v, topn=-1.0: mungers.relevel_by_freq(
        _as_vec(v), int(topn)),
    "getrow": lambda R, fr: mungers.getrow(_as_frame(fr)),
    "flatten": lambda R, fr: mungers.flatten(_as_frame(fr)),
    "as.Date": lambda R, v, fmt: mungers.as_date(_as_vec(v), str(fmt)),
    "week": lambda R, v: mungers.week(_as_vec(v)),
    "listTimeZones": lambda R: mungers.list_timezones(),
    "getTimeZone": lambda R: mungers.get_timezone(),
    "setTimeZone": lambda R, tz: mungers.set_timezone(str(tz)),
    "isax": lambda R, fr, nw, mc, oc=0.0: mungers.isax(
        _as_frame(fr), int(nw), int(mc), bool(oc)),
    "num_valid_substrings": lambda R, v, path: mungers.num_valid_substrings(
        _as_vec(v), str(path)),
    "apply": _apply,
    "ddply": _ddply,
    "tf-idf": lambda R, fr, did, tid, pre=True, cs=True: mungers.tf_idf(
        _as_frame(fr), int(did), int(tid), bool(pre), bool(cs)),
    # NA-tolerant reducer aliases + axis/count reducers (`prims/reducers`)
    **{alias: _prim_reduce(base) for alias, base in
       (("sumNA", "sum"), ("maxNA", "max"), ("minNA", "min"),
        ("prod.na", "prod"))},
    "sumaxis": lambda R, fr, na_rm=False, axis=0.0: _sumaxis(
        _as_frame(fr), bool(na_rm), int(axis)),
    "naCnt": lambda R, fr: [float(v.nacnt())
                            for v in _as_frame(fr).vecs],
    "any.na": lambda R, fr: float(any(v.nacnt() > 0
                                      for v in _as_frame(fr).vecs)),
    "%/%": _prim_binop("%/%"),
    # uniform random column keyed to the frame's rows (`AstRunif`) — the
    # h2o-py split_frame building block
    "h2o.runif": lambda R, fr, seed=-1: (lambda f: Vec.from_numpy(
        np.random.default_rng(
            None if seed in (-1, None) else int(seed)).random(
                f.nrow).astype(np.float32)))(_as_frame(fr)),
    # fourth wave: registry stragglers closing the diff against the
    # reference's prim set (`water/rapids/ast/prims/**` str() names)
    "%": _prim_binop("%%"),                      # AstMod's registered name
    ",": lambda R, *vals: (vals[-1] if vals else None),  # AstComma sequencing
    "as.character": lambda R, v: strmod.ascharacter(_as_vec(v)),
    "strlen": lambda R, v: strmod.nchar(_as_vec(v)),     # AstStrLength
    "ls": lambda R: Frame(["key"], [Vec(
        None, len(STORE.keys()), type="string",
        host_data=np.asarray(sorted(STORE.keys()), dtype=object))]),
    # (filterNACols fr frac): indices of columns whose NA count stays BELOW
    # nrow*frac (AstFilterNaCols.java:32-46)
    "filterNACols": lambda R, fr, frac: [
        float(i) for i, nm in enumerate(_as_frame(fr).names)
        if _as_frame(fr).vec(nm).nacnt() < _as_frame(fr).nrow * float(frac)],
    "model.reset.threshold": _reset_threshold_prim,
    "segment_models_as_frame": lambda R, key: _resolve_model(key).as_frame(),
    # `AstWord2VecToFrame` — embeddings as a [Word, V1..VD] frame
    "word2vec.to.frame": lambda R, key: _w2v_to_frame(_resolve_model(key)),
    "PermutationVarImp": _permutation_varimp_prim,
    "makeLeaderboard": _make_leaderboard_prim,
    # `AstFairnessMetrics` — disparate-impact analysis; returns a MAP of
    # frames ('overview' + per-group threshold tables)
    "fairnessMetrics": lambda R, model, fr, pcols, ref, fav:
        _fairness_metrics_prim(R, model, fr, pcols, ref, fav),
    # `AstTransformFrame` — model.transform (TargetEncoder et al.);
    # lambdas defer the name lookups to call time (defs live below)
    "transform": lambda R, m, fr: _transform_frame_prim(R, m, fr),
    # `AstScale` in-place flavor: same standardization, the input frame's
    # vecs are REBOUND (callers holding the key see scaled data)
    "scale_inplace": lambda R, fr, center=True, scale=True:
        _scale_inplace_prim(R, fr, center, scale),
    # `AstGroupedPermute` — within-group cross pairing of debit/credit rows
    "grouped_permute": lambda R, fr, perm_col, gb, permute_by, keep_col:
        mungers.grouped_permute(_as_frame(fr), int(perm_col),
                                [int(g) for g in (gb if isinstance(gb, list)
                                                  else [gb])],
                                int(permute_by), int(keep_col)),
}


def _as_strlist(x):
    return x if isinstance(x, list) else [x]


def _fairness_metrics_prim(R, model, fr, pcols, ref, fav):
    from .fairness import fairness_metrics

    return fairness_metrics(_resolve_model(model), _as_frame(fr),
                            [str(c) for c in _as_strlist(pcols)],
                            (None if not ref else
                             [str(c) for c in _as_strlist(ref)]), str(fav))


def _transform_frame_prim(R, model, fr):
    m = _resolve_model(model)
    fn = getattr(m, "transform", None)
    if fn is None:
        raise ValueError(f"model {getattr(m, 'key', m)} does not support "
                         "transform (TargetEncoder-style models only)")
    return fn(_as_frame(fr))


def _scale_inplace_prim(R, fr, center=True, scale=True):
    src = _as_frame(fr)
    out = advmath.scale_frame(src, _maybe_list(center), _maybe_list(scale))
    # mutate the shared Vec OBJECTS (rapids evaluation may hand the prim a
    # shallow frame copy, but the vecs are the DKV-resident ones): swap
    # their device arrays and invalidate rollups — every holder of the
    # frame key observes the scaled data (`AstScale.java:67-72`)
    for n in src.names:
        v, nv = src.vec(n), out.vec(n)
        if nv is not v and nv.data is not None:
            v.data = nv.data  # property setter: lock + spill/CLEANER upkeep
            v.exact_data = None
            v.modified()
    return src


def _maybe_list(x):
    if isinstance(x, list):
        return [float(v) for v in x]
    return bool(x)


def _asfactor(v: Vec) -> Vec:
    return strmod.asfactor(v)


def _asnumeric(v: Vec) -> Vec:
    if not v.is_categorical():
        return v
    return Vec.from_numpy(v.to_numpy(), type="real")


def rapids_exec(expr: str, session: Session | None = None):
    """Module-level convenience — `Rapids.exec(String, Session)`."""
    return Rapids(session).exec(expr)
