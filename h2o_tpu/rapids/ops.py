"""Rapids core ops — element-wise math, comparisons, reducers, cumulants.

Analog of `water/rapids/ast/prims/{math,operators,reducers,timeseries}` (part
of the 24,566-LoC rapids layer). Each op is a device-side vectorized kernel
over the row-sharded Vec data; NA propagation comes free from NaN arithmetic
(the reference threads NA checks through every `AstBinOp.op`).

H2O semantics preserved:
- comparisons return 0/1 numeric vecs, NA in → NA out
- `&&`/`||` use H2O's ternary-logic NA rules (NA && 0 == 0, NA || 1 == 1)
- reducers have `na_rm` variants
- integer division / modulo follow H2O (Java) truncation semantics
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, T_INT, T_NUM, Vec


def _data(v):
    if isinstance(v, Vec):
        return v.data
    return v  # scalar


def _nrow(*vs):
    for v in vs:
        if isinstance(v, Vec):
            return v.nrow
    raise ValueError("need at least one Vec")


def _mask(v: Vec):
    return jnp.arange(v.data.shape[0]) < v.nrow


# ---------------------------------------------------------------------------
# binary / unary element-wise
# ---------------------------------------------------------------------------
_BINOPS = {
    "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply, "/": jnp.divide,
    "^": jnp.power,
    # Java truncated remainder (sign follows dividend): AstMod/AstModR both
    # evaluate `l % r` on doubles (operators/AstMod.java:11, AstModR.java:11),
    # so (% -7 3) == -1, not the floored +2. x % 0 is NaN on Java doubles.
    "%%": lambda a, b: jnp.where(b == 0, jnp.nan, jnp.fmod(a, b)),
    # AstIntDiv: `(int) l / (int) r` — each operand truncates BEFORE the
    # divide (so intDiv(-7.9, 3.9) == -7/3 == -2), NaN when (int) r == 0.
    # AstIntDivR (`%/%`): `(int) (l / r)` — the real quotient truncates.
    # Divergence: Java's (int) of NaN/±Inf collapses to 0/Integer.MAX_VALUE;
    # we propagate NaN and return NaN on zero divisors instead.
    "intDiv": lambda a, b: jnp.where(jnp.trunc(b) == 0, jnp.nan,
                                     jnp.trunc(jnp.trunc(a) / jnp.trunc(b))),
    "%/%": lambda a, b: jnp.where(b == 0, jnp.nan, jnp.trunc(a / b)),
}

_CMPOPS = {
    "==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less, "<=": jnp.less_equal,
    ">": jnp.greater, ">=": jnp.greater_equal,
}


#: numpy float64 twins of the device tables — the reference computes in
#: double everywhere, so columns whose values don't round-trip f32 (they
#: carry an exact host sidecar) evaluate element-wise ops host-side in f64.
#: Device f32 remains the path for exactly-representable data and big frames.
_NP_BINOPS = {
    "+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide,
    "^": np.float_power,
    "%%": lambda a, b: np.where(b == 0, np.nan, np.fmod(a, b)),
    "intDiv": lambda a, b: np.where(np.trunc(b) == 0, np.nan,
                                    np.trunc(np.trunc(a) / np.trunc(b))),
    "%/%": lambda a, b: np.where(b == 0, np.nan, np.trunc(np.divide(a, b))),
}

_NP_CMPOPS = {
    "==": np.equal, "!=": np.not_equal, "<": np.less, "<=": np.less_equal,
    ">": np.greater, ">=": np.greater_equal,
}


def _exact_np(v, nrow: int):
    if isinstance(v, Vec):
        return v.to_numpy().astype(np.float64)
    return np.float64(v)


def _wants_f64(v) -> bool:
    return isinstance(v, Vec) and v.exact_data is not None


def _binop_host(op: str, l, r, nrow: int) -> Vec:
    a, b = _exact_np(l, nrow), _exact_np(r, nrow)
    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        if op in _NP_BINOPS:
            return Vec.from_numpy(np.asarray(_NP_BINOPS[op](a, b),
                                             dtype=np.float64))
        if op in _NP_CMPOPS:
            res = _NP_CMPOPS[op](a, b).astype(np.float64)
            res = np.where(np.isnan(a) | np.isnan(b), np.nan, res)
            return Vec.from_numpy(res, type=T_INT)
        if op in ("&", "&&"):
            out = np.where((a == 0) | (b == 0), 0.0,
                           np.where(np.isnan(a) | np.isnan(b), np.nan, 1.0))
            return Vec.from_numpy(out, type=T_INT)
        if op in ("|", "||"):
            a1 = (a != 0) & ~np.isnan(a)
            b1 = (b != 0) & ~np.isnan(b)
            out = np.where(a1 | b1, 1.0,
                           np.where(np.isnan(a) | np.isnan(b), np.nan, 0.0))
            return Vec.from_numpy(out, type=T_INT)
    raise ValueError(f"unknown op {op!r}")


def binop(op: str, l, r) -> Vec:
    nrow = _nrow(l, r)
    if _wants_f64(l) or _wants_f64(r):
        return _binop_host(op, l, r, nrow)
    a, b = _data(l), _data(r)
    if op in _BINOPS:
        out = _BINOPS[op](a, b)
        return Vec.from_device(out, nrow)
    if op in _CMPOPS:
        res = _CMPOPS[op](a, b).astype(jnp.float32)
        if isinstance(l, Vec):
            res = jnp.where(jnp.isnan(_data(l)), jnp.nan, res)
        if isinstance(r, Vec):
            res = jnp.where(jnp.isnan(_data(r)), jnp.nan, res)
        return Vec.from_device(res, nrow, type=T_INT)
    if op in ("&", "&&"):
        return _logical_and(l, r)
    if op in ("|", "||"):
        return _logical_or(l, r)
    raise ValueError(f"unknown op {op!r}")


def _logical_and(l, r) -> Vec:
    nrow = _nrow(l, r)
    a, b = _data(l), _data(r)
    az = a == 0
    bz = b == 0
    ana = jnp.isnan(a)
    bna = jnp.isnan(b)
    out = jnp.where(az | bz, 0.0,
                    jnp.where(ana | bna, jnp.nan, 1.0))
    return Vec.from_device(out, nrow, type=T_INT)


def _logical_or(l, r) -> Vec:
    nrow = _nrow(l, r)
    a, b = _data(l), _data(r)
    a1 = (a != 0) & ~jnp.isnan(a)
    b1 = (b != 0) & ~jnp.isnan(b)
    ana = jnp.isnan(a)
    bna = jnp.isnan(b)
    out = jnp.where(a1 | b1, 1.0, jnp.where(ana | bna, jnp.nan, 0.0))
    return Vec.from_device(out, nrow, type=T_INT)


_UNARY = {
    "abs": jnp.abs, "ceiling": jnp.ceil, "floor": jnp.floor,
    "trunc": jnp.trunc, "sign": jnp.sign,
    "exp": jnp.exp, "expm1": jnp.expm1,
    "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)) * jnp.sign(
        jnp.where(x > 0, 1.0, jnp.cos(jnp.pi * jnp.floor(x)))),
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "trigamma": lambda x: jax.scipy.special.polygamma(1, x),
    "cospi": lambda x: jnp.cos(jnp.pi * x),
    "sinpi": lambda x: jnp.sin(jnp.pi * x),
    "tanpi": lambda x: jnp.tan(jnp.pi * x),
    "not": lambda x: jnp.where(jnp.isnan(x), jnp.nan, (x == 0).astype(jnp.float32)),
    "isna": None,  # special-cased (NA -> 1, never NA)
}


def unop(op: str, v: Vec) -> Vec:
    if op == "isna":
        if v.data is None:  # string column: host-side None check
            out = np.array([1.0 if x is None else 0.0
                            for x in v.host_data], np.float32)
            return Vec.from_numpy(out, type=T_INT)
        out = jnp.isnan(v.data).astype(jnp.float32)
        out = jnp.where(_mask(v), out, jnp.nan)  # padding stays NA
        return Vec.from_device(out, v.nrow, type=T_INT)
    if op == "round":
        return round_digits(v, 0)
    fn = _UNARY[op]
    return Vec.from_device(fn(v.data), v.nrow)


def round_digits(v: Vec, digits: int = 0) -> Vec:
    scale = 10.0 ** digits
    # jnp.round is round-half-even, matching R/H2O rounding
    return Vec.from_device(jnp.round(v.data * scale) / scale, v.nrow)


def signif(v: Vec, digits: int) -> Vec:
    x = v.data
    mag = jnp.where(x == 0, 1.0, jnp.power(
        10.0, digits - 1 - jnp.floor(jnp.log10(jnp.abs(jnp.where(x == 0, 1.0, x))))))
    return Vec.from_device(jnp.round(x * mag) / mag, v.nrow)


def ifelse(test, yes, no) -> Vec:
    nrow = _nrow(test)
    t = _data(test)
    out = jnp.where(jnp.isnan(t), jnp.nan,
                    jnp.where(t != 0, _data(yes), _data(no)))
    return Vec.from_device(out, nrow)


# ---------------------------------------------------------------------------
# reducers (`water/rapids/ast/prims/reducers`)
# ---------------------------------------------------------------------------
def _valid(v: Vec):
    return ~jnp.isnan(v.data)


def _reduce_host(op: str, v: Vec, na_rm: bool) -> float:
    x = v.to_numpy().astype(np.float64)
    ok = ~np.isnan(x)
    if not na_rm and not ok.all():
        return float("nan")
    xv = x[ok]
    if xv.size == 0 and op in ("sum", "prod", "min", "max", "mean", "median"):
        return float("nan") if op not in ("sum", "prod") else \
            (0.0 if op == "sum" else 1.0)
    fns = {"sum": np.sum, "prod": np.prod, "min": np.min, "max": np.max,
           "mean": np.mean, "median": np.median,
           "sd": lambda a: np.std(a, ddof=1), "sdev": lambda a: np.std(a, ddof=1),
           "var": lambda a: np.var(a, ddof=1)}
    if op in fns:
        return float(fns[op](xv))
    if op == "all":
        return bool(np.all(xv != 0))
    if op == "any":
        return bool(np.any(xv != 0))
    if op == "nacnt":
        return v.nacnt()
    raise ValueError(f"unknown reducer {op!r}")


def reduce_op(op: str, v: Vec, na_rm: bool = True) -> float:
    if _wants_f64(v):
        return _reduce_host(op, v, na_rm)
    ok = _valid(v)
    x = v.data
    has_na = bool(jnp.sum(~ok) > (v.plen - v.nrow))
    if not na_rm and has_na:
        return float("nan")
    if op == "sum":
        return float(jnp.sum(jnp.where(ok, x, 0.0)))
    if op == "prod":
        return float(jnp.prod(jnp.where(ok, x, 1.0)))
    if op == "min":
        return float(jnp.min(jnp.where(ok, x, jnp.inf)))
    if op == "max":
        return float(jnp.max(jnp.where(ok, x, -jnp.inf)))
    if op == "mean":
        r = v.rollups()
        return r.mean
    if op in ("sd", "sdev"):
        return v.rollups().sigma
    if op == "var":
        return v.rollups().sigma ** 2
    if op == "median":
        from ..models.quantile import quantiles_device

        return float(quantiles_device(v.data, v.nrow, (0.5,))[0])
    if op == "all":
        return bool(jnp.all(jnp.where(ok, x != 0, True)))
    if op == "any":
        return bool(jnp.any(jnp.where(ok, x != 0, False)))
    if op == "nacnt":
        return v.nacnt()
    raise ValueError(f"unknown reducer {op!r}")


def cumulative(op: str, v: Vec) -> Vec:
    """cumsum/cumprod/cummin/cummax with NA propagation from first NA on."""
    if _wants_f64(v):
        x = v.to_numpy().astype(np.float64)
        hf = {"cumsum": np.cumsum, "cumprod": np.cumprod,
              "cummin": np.minimum.accumulate,
              "cummax": np.maximum.accumulate}[op]
        out = hf(x)  # NaN poisons every later prefix naturally
        return Vec.from_numpy(out)
    # lax.cummin/cummax rather than jnp.minimum.accumulate: the ufunc
    # .accumulate methods only exist on jax >= 0.6
    fns = {"cumsum": jnp.cumsum, "cumprod": jnp.cumprod,
           "cummin": jax.lax.cummin, "cummax": jax.lax.cummax}
    neutral = {"cumsum": 0.0, "cumprod": 1.0, "cummin": jnp.inf,
               "cummax": -jnp.inf}[op]
    ok = _valid(v) & _mask(v)
    filled = jnp.where(ok, v.data, neutral)
    out = fns[op](filled)
    # NA poisoning: once an in-range NA appears, all later outputs are NA
    na_seen = jnp.cumsum((~ok & _mask(v)).astype(jnp.int32)) > 0
    out = jnp.where(na_seen, jnp.nan, out)
    return Vec.from_device(out, v.nrow)


# ---------------------------------------------------------------------------
# table / unique / histogram (`prims/advmath`)
# ---------------------------------------------------------------------------
def table(v: Vec) -> Frame:
    """Counts per level/integer value — `AstTable`."""
    host = v.to_numpy()
    ok = ~np.isnan(host)
    vals, counts = np.unique(host[ok], return_counts=True)
    if v.is_categorical() and v.domain:
        names = [v.domain[int(x)] for x in vals]
        c1 = Vec.from_numpy(np.arange(len(vals), dtype=np.float32), type=T_CAT,
                            domain=names)
    else:
        c1 = Vec.from_numpy(vals.astype(np.float32))
    return Frame(["row", "count"],
                 [c1, Vec.from_numpy(counts.astype(np.float32), type=T_INT)])


def unique(v: Vec) -> Vec:
    host = v.to_numpy()
    vals = np.unique(host[~np.isnan(host)])
    if v.is_categorical():
        return Vec.from_numpy(vals.astype(np.float32), type=T_CAT, domain=v.domain)
    return Vec.from_numpy(vals.astype(np.float32))


def hist(v: Vec, breaks: int = 20):
    r = v.rollups()
    edges = np.linspace(r.mins, r.maxs, breaks + 1)
    x = v.data
    ok = _valid(v) & _mask(v)
    b = jnp.clip(jnp.searchsorted(jnp.asarray(edges[1:-1]), x, side="right"),
                 0, breaks - 1)
    oh = jax.nn.one_hot(b, breaks, dtype=jnp.float32) * ok[:, None]
    counts = jnp.sum(oh, axis=0)
    return np.asarray(counts), edges


# ---------------------------------------------------------------------------
# time ops (`prims/time`) — columns are ms since epoch
# ---------------------------------------------------------------------------
def time_part(v: Vec, part: str) -> Vec:
    ms = v.to_numpy().astype("float64")
    ok = ~np.isnan(ms)
    dt = np.full(ms.shape, np.datetime64("NaT"), dtype="datetime64[ms]")
    dt[ok] = ms[ok].astype("int64").astype("datetime64[ms]")
    Y = dt.astype("datetime64[Y]")
    M = dt.astype("datetime64[M]")
    D = dt.astype("datetime64[D]")
    out = {
        "year": Y.astype(float) + 1970,
        "month": (M - Y).astype(float) + 1,
        "day": (D - M).astype(float) + 1,
        "dayOfWeek": ((D.astype("int64") + 3) % 7).astype(float),  # 0=Mon
        "hour": ((dt - D).astype("timedelta64[h]")).astype(float),
        "minute": ((dt - dt.astype("datetime64[h]")).astype("timedelta64[m]")).astype(float),
        "second": ((dt - dt.astype("datetime64[m]")).astype("timedelta64[s]")).astype(float),
        "millis": ((dt - dt.astype("datetime64[s]")).astype("timedelta64[ms]")).astype(float),
    }[part]
    out = np.where(ok, out, np.nan).astype(np.float32)
    return Vec.from_numpy(out, type=T_INT)
