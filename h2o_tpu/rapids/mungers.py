"""Rapids third wave: frame mutation, repeaters, search/filter/munge prims.

Reference: `water/rapids/ast/prims/{assign,repeaters,mungers,filters,advmath,
reducers,time,timeseries,models}` — the remaining primitives h2o-py/h2o-r
emit that the first two waves didn't cover. Wire names match the reference
``str()`` registrations exactly (e.g. `AstAppend` "append",
`AstRectangleAssign` ":=", `AstRepLen` "rep_len", `AstDropDuplicates`
"dropdup", `AstMad` "h2o.mad", `AstDistance` "distance").

Device placement: bulk row-wise math (distance matrices, PAA/iSAX, mode
counts) runs on device via jnp; structural edits (rectangle assign, domain
surgery, dedup) round-trip through numpy like the reference's NewChunk
copies — they are O(selection), not hot-loop code.
"""

from __future__ import annotations

import datetime as _dt

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, T_INT, T_NUM, T_STR, T_TIME, Vec


# ---------------------------------------------------------------------------
# assign (`prims/assign/AstAppend.java`, `AstRectangleAssign.java`)
# ---------------------------------------------------------------------------
def _const_vec(value, nrow: int) -> Vec:
    if isinstance(value, str):
        # `Vec.makeCon(String)`: constant categorical with a 1-level domain
        return Vec.from_numpy(np.zeros(nrow, dtype=np.float32),
                              type=T_CAT, domain=[value])
    return Vec.from_numpy(np.full(nrow, float(value), dtype=np.float32))


def append(dst: Frame, src, name: str) -> Frame:
    """(append dst src "name") — attach a column; number/str sources become
    constant columns (`AstAppend.java:44-60`)."""
    out = Frame(list(dst.names), list(dst.vecs))
    if isinstance(src, Frame):
        if src.ncol != 1:
            raise ValueError("Can only append one column")
        vec = src.vec(0)
    elif isinstance(src, Vec):
        vec = src
    else:
        vec = _const_vec(src, dst.nrow)
    if vec.nrow != dst.nrow and dst.ncol:
        raise ValueError(f"append: row mismatch {vec.nrow} vs {dst.nrow}")
    if name in out.names:
        out.replace(name, vec)
    else:
        out.add(str(name), vec)
    return out


def _assign_into(col: Vec, rows, src_col, nrow: int) -> Vec:
    """Overwrite `rows` of one column; src_col is a Vec (len == selection),
    a number, a string (categorical level / string value), or NaN."""
    if col.is_string():
        vals = np.array(col.host_data, dtype=object)
        if isinstance(src_col, Vec):
            sv = (src_col.host_data if src_col.is_string()
                  else src_col.to_numpy().astype(object))
            vals[rows] = sv
        else:
            vals[rows] = src_col if isinstance(src_col, str) else (
                None if src_col is None or (isinstance(src_col, float)
                                            and np.isnan(src_col))
                else float(src_col))
        return Vec.from_numpy(vals)

    data = col.to_numpy().astype(np.float64)
    domain = list(col.domain) if col.domain else None
    if isinstance(src_col, Vec):
        sv = src_col.to_numpy()
        if col.is_categorical() and src_col.is_categorical():
            # remap source levels into the destination domain, extending it
            code_map = np.full(len(src_col.domain or []), np.nan)
            for i, lvl in enumerate(src_col.domain or []):
                if lvl not in domain:
                    domain.append(lvl)
                code_map[i] = domain.index(lvl)
            ok = ~np.isnan(sv)
            mapped = np.full_like(sv, np.nan, dtype=np.float64)
            mapped[ok] = code_map[sv[ok].astype(int)]
            sv = mapped
        data[rows] = sv
    elif isinstance(src_col, str):
        if not col.is_categorical():
            raise ValueError("string assignment needs a categorical column")
        if src_col not in domain:
            domain.append(src_col)
        data[rows] = domain.index(src_col)
    else:
        data[rows] = (np.nan if src_col is None else float(src_col))
    return Vec.from_numpy(data.astype(np.float32), type=col.type,
                          domain=domain)


def rectangle_assign(dst: Frame, src, cols, rows) -> Frame:
    """(:= dst src col_expr row_expr) — `AstRectangleAssign.java`: overwrite a
    row × column slice; conceptually a fresh frame (COW in the reference)."""
    nrow = dst.nrow
    rows = np.arange(nrow) if rows is None else np.asarray(rows)
    if rows.dtype == bool:
        rows = np.where(rows)[0]
    out = Frame(list(dst.names), list(dst.vecs))
    col_list = cols if isinstance(cols, list) else [cols]
    # strictly 0..nrow-1 IN ORDER: a permuted or duplicated full-length row
    # list is a scatter, not a column replacement
    whole_column = len(rows) == nrow and \
        (nrow == 0 or bool(np.array_equal(rows, np.arange(nrow))))
    for k, ci in enumerate(col_list):
        ci = int(ci)
        if whole_column and isinstance(src, (Frame, Vec)):
            # assigning a full column REPLACES it, adopting the source's
            # type/domain (h2o-py `f[col] = numeric_frame` drops the old
            # enum domain — `AstRectangleAssign` whole-vec path)
            sv = src.vec(k) if isinstance(src, Frame) else src
            if sv.nrow == nrow:
                out._vecs[ci] = sv
                continue
        if isinstance(src, Frame):
            if src.ncol != len(col_list):
                raise ValueError(f"Frame src has {src.ncol} cols; assigning "
                                 f"{len(col_list)}")
            sv = src.vec(k)
            if sv.nrow != len(rows):
                raise ValueError(f"src rows {sv.nrow} != selection "
                                 f"{len(rows)}")
            src_col = sv
        elif isinstance(src, Vec):
            src_col = src
        else:
            src_col = src
        out._vecs[ci] = _assign_into(dst.vec(ci), rows, src_col, nrow)
    return out


# ---------------------------------------------------------------------------
# repeaters (`prims/repeaters/Ast{Seq,SeqLen,RepLen}.java`)
# ---------------------------------------------------------------------------
def seq(frm: float, to: float, by: float) -> Vec:
    if by == 0:
        raise ValueError("seq: by must be non-zero")
    n = int(np.floor((to - frm) / by + 1e-10)) + 1
    if n <= 0:
        raise ValueError("seq: wrong sign of 'by'")
    return Vec.from_numpy((frm + by * np.arange(n)).astype(np.float64))


def seq_len(n: float) -> Vec:
    if int(n) <= 0:
        raise ValueError(f"Argument to seq_len must be a positive number: {n}")
    return Vec.from_numpy(np.arange(1, int(n) + 1, dtype=np.float64))


def rep_len(x, length: int) -> Vec:
    length = int(length)
    if isinstance(x, Frame):
        x = x.vec(0)
    if isinstance(x, Vec):
        reps = int(np.ceil(length / max(x.nrow, 1)))
        vals = np.tile(x.to_numpy(), reps)[:length]
        return Vec.from_numpy(vals, type=x.type,
                              domain=list(x.domain) if x.domain else None)
    return Vec.from_numpy(np.full(length, float(x), dtype=np.float64))


# ---------------------------------------------------------------------------
# advmath: mode / distance / hist breaks algos / modulo kfold
# ---------------------------------------------------------------------------
def mode(v: Vec) -> float:
    """(mode col) — most frequent level of a categorical (`AstMode.java`)."""
    if not v.is_categorical():
        raise ValueError("mode expects a categorical column")
    x = v.to_numpy()
    x = x[~np.isnan(x)].astype(int)
    if not x.size:
        return float("nan")
    return float(np.bincount(x).argmax())


def distance(x: Frame, y: Frame, measure: str) -> Frame:
    """(distance X Y measure) — pairwise distances, N×M output
    (`AstDistance.java`); one MXU matmul per measure on device."""
    measure = measure.lower()
    if measure not in ("cosine", "cosine_sq", "l1", "l2"):
        raise ValueError(f"Invalid distance measure provided: {measure}")
    if x.ncol != y.ncol:
        raise ValueError(f"Frames must have the same number of cols, found "
                         f"{x.ncol} and {y.ncol}")
    X = jnp.nan_to_num(x.as_matrix())[: x.nrow]
    Y = jnp.nan_to_num(y.as_matrix())[: y.nrow]
    if measure == "l1":
        D = jnp.sum(jnp.abs(X[:, None, :] - Y[None, :, :]), axis=-1)
    else:
        G = X @ Y.T
        nx = jnp.sum(X * X, axis=1)
        ny = jnp.sum(Y * Y, axis=1)
        if measure == "l2":
            D = jnp.sqrt(jnp.maximum(nx[:, None] + ny[None, :] - 2 * G, 0.0))
        elif measure == "cosine":
            D = G / jnp.maximum(jnp.sqrt(nx)[:, None] * jnp.sqrt(ny)[None, :],
                                1e-30)
        else:  # cosine_sq
            D = (G * G) / jnp.maximum(nx[:, None] * ny[None, :], 1e-30)
    Dn = np.asarray(D)
    return Frame([f"C{j + 1}" for j in range(Dn.shape[1])],
                 [Vec.from_numpy(Dn[:, j]) for j in range(Dn.shape[1])])


def _hist_nbins(v: Vec, algo: str) -> int:
    """Break-count heuristics (`AstHist.java` sturges/rice/sqrt/doane/scott/fd)."""
    n = v.nrow - v.nacnt()
    x = v.to_numpy()
    x = x[~np.isnan(x)]
    rng = float(x.max() - x.min()) if x.size else 1.0
    if algo == "sturges":
        return max(int(np.ceil(np.log2(max(n, 2)) + 1)), 1)
    if algo == "rice":
        return max(int(np.ceil(2 * n ** (1.0 / 3))), 1)
    if algo == "sqrt":
        return max(int(np.ceil(np.sqrt(n))), 1)
    if algo == "doane":
        if n <= 2:
            return 1
        g1 = float(np.abs(
            np.mean((x - x.mean()) ** 3) / max(np.std(x) ** 3, 1e-30)))
        sg = np.sqrt(6.0 * (n - 2) / ((n + 1.0) * (n + 3)))
        return max(int(np.ceil(1 + np.log2(n) + np.log2(1 + g1 / sg))), 1)
    if algo == "scott":
        h = 3.5 * float(np.std(x)) / max(n, 1) ** (1.0 / 3)
        return max(int(np.ceil(rng / max(h, 1e-30))), 1)
    if algo == "fd":
        q75, q25 = np.percentile(x, [75, 25]) if x.size else (1.0, 0.0)
        h = 2.0 * (q75 - q25) / max(n, 1) ** (1.0 / 3)
        return max(int(np.ceil(rng / max(h, 1e-30))), 1) if h > 0 else 1
    return _hist_nbins(v, "sturges")


def hist(v: Vec, breaks) -> Frame:
    """(hist col breaks) — breaks may be an algo name, a count, or explicit
    break points; output columns mirror `AstHist.java`: breaks/counts/
    mids_true/mids."""
    x = v.to_numpy()
    x = x[~np.isnan(x)]
    if isinstance(breaks, str):
        edges = np.linspace(x.min(), x.max(), _hist_nbins(v, breaks.lower()) + 1)
    elif isinstance(breaks, list):
        edges = np.asarray([float(b) for b in breaks])
    else:
        edges = np.linspace(x.min(), x.max(), max(int(breaks), 1) + 1)
    counts, _ = np.histogram(x, bins=edges)
    mids = 0.5 * (edges[:-1] + edges[1:])
    # mids_true = mean of members per bin (reference HistTask computes this)
    which = np.clip(np.digitize(x, edges) - 1, 0, len(counts) - 1)
    sums = np.bincount(which, weights=x, minlength=len(counts))
    mids_true = np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)
    return Frame(
        ["breaks", "counts", "mids_true", "mids"],
        [Vec.from_numpy(edges[1:]),
         Vec.from_numpy(counts.astype(np.float64)),
         Vec.from_numpy(mids_true),
         Vec.from_numpy(mids)])


def modulo_kfold_column(v: Vec, n: int) -> Vec:
    idx = np.arange(v.nrow, dtype=np.int64)
    return Vec.from_numpy((idx % int(n)).astype(np.float32), type=T_INT)


def mad(fr: Frame, combine: str = "interpolate",
        constant: float = 1.4826) -> float:
    """(h2o.mad fr combine const) — `AstMad.java`: const·median(|x−median|)."""
    v = fr.vec(0)
    if v.nacnt() > 0:
        return float("nan")
    x = v.to_numpy()
    med = float(np.median(x))
    return constant * float(np.median(np.abs(x - med)))


def perfect_auc(probs: Vec, acts: Vec) -> float:
    """(perfectAUC p y) — exact AUC by rank statistic (`AstPerfectAUC.java`)."""
    p = probs.to_numpy()
    y = acts.to_numpy()
    ok = ~(np.isnan(p) | np.isnan(y))
    p, y = p[ok], y[ok].astype(int)
    n1 = int(y.sum())
    n0 = len(y) - n1
    if n0 == 0 or n1 == 0:
        return float("nan")
    order = np.argsort(p, kind="stable")
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    # midranks for ties
    ps = p[order]
    i = 0
    while i < len(ps):
        j = i
        while j + 1 < len(ps) and ps[j + 1] == ps[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    return float((ranks[y == 1].sum() - n1 * (n1 + 1) / 2.0) / (n0 * n1))


# ---------------------------------------------------------------------------
# filters (`prims/filters/dropduplicates`)
# ---------------------------------------------------------------------------
def dropdup(fr: Frame, cols, keep: str = "first") -> Frame:
    """(dropdup fr [cols] keep) — drop duplicate rows by key columns."""
    idxs = cols if isinstance(cols, list) else [cols]
    keys = []
    for c in idxs:
        v = fr.vec(int(c)) if not isinstance(c, str) else fr.vec(c)
        keys.append(v.host_data if v.is_string() else v.to_numpy())
    tags = [tuple(None if (isinstance(k[i], float) and np.isnan(k[i]))
                  else k[i] for k in keys) for i in range(fr.nrow)]
    seen: dict = {}
    order = range(fr.nrow) if keep == "first" else range(fr.nrow - 1, -1, -1)
    for i in order:
        seen.setdefault(tags[i], i)
    pick = np.array(sorted(seen.values()), dtype=np.int64)
    return fr.take(pick)


# ---------------------------------------------------------------------------
# mungers: domains, types, shapes
# ---------------------------------------------------------------------------
def nlevels(v: Vec) -> float:
    return float(len(v.domain)) if v.domain else 0.0


def any_factor(fr: Frame) -> float:
    return float(any(v.is_categorical() for v in fr.vecs))


def columns_by_type(fr: Frame, coltype: str = "numeric") -> list[float]:
    """(columnsByType fr type) — indices of columns of the given type
    (`AstColumnsByType.java`)."""
    coltype = coltype.lower()
    picks = []
    for i, v in enumerate(fr.vecs):
        is_num = v.type in (T_NUM, T_INT) and not v.is_categorical()
        if ((coltype == "numeric" and is_num)
                or (coltype == "categorical" and v.is_categorical())
                or (coltype == "string" and v.is_string())
                or (coltype == "time" and v.type == T_TIME)
                or (coltype == "bad" and v.type == "bad")
                or (coltype == "uuid" and v.type == "uuid")):
            picks.append(float(i))
    return picks


def set_level(v: Vec, level: str) -> Vec:
    """(setLevel col "lvl") — constant column at one existing level."""
    if not v.is_categorical() or level not in (v.domain or []):
        raise ValueError(f"setLevel: '{level}' not in domain")
    code = float(v.domain.index(level))
    return Vec.from_numpy(np.full(v.nrow, code, dtype=np.float32),
                          type=T_CAT, domain=list(v.domain))


def append_levels(v: Vec, levels) -> Vec:
    """(appendLevels col [lvls]) — widen the domain, data unchanged."""
    if not v.is_categorical():
        raise ValueError("appendLevels expects a categorical column")
    dom = list(v.domain)
    for l in ([levels] if isinstance(levels, str) else levels):
        if l not in dom:
            dom.append(str(l))
    return Vec.from_numpy(v.to_numpy(), type=T_CAT, domain=dom)


def relevel_by_freq(v: Vec, top_n: int = -1) -> Vec:
    """(relevel.by.freq col topN) — reorder domain by descending frequency."""
    if not v.is_categorical():
        raise ValueError("relevel.by.freq expects a categorical column")
    x = v.to_numpy()
    ok = ~np.isnan(x)
    counts = np.bincount(x[ok].astype(int), minlength=len(v.domain))
    order = np.argsort(-counts, kind="stable")
    if top_n > 0:  # only promote the top_n most frequent, keep the rest as-is
        promoted = list(order[:top_n])
        rest = [i for i in range(len(v.domain)) if i not in promoted]
        order = np.array(promoted + rest)
    new_dom = [v.domain[i] for i in order]
    remap = np.empty(len(v.domain))
    remap[order] = np.arange(len(order))
    out = np.where(ok, remap[np.clip(x, 0, None).astype(int)], np.nan)
    return Vec.from_numpy(out.astype(np.float32), type=T_CAT, domain=new_dom)


def getrow(fr: Frame) -> list:
    """(getrow fr) — single-row frame to a row of values (`AstGetrow.java`)."""
    if fr.nrow != 1:
        raise ValueError(f"getrow requires a frame with exactly 1 row; "
                         f"got {fr.nrow}")
    out = []
    for v in fr.vecs:
        if v.is_string():
            out.append(v.host_data[0])
        elif v.is_categorical():
            c = v.to_numpy()[0]
            out.append(None if np.isnan(c) else v.domain[int(c)])
        else:
            out.append(float(v.to_numpy()[0]))
    return out


def flatten(fr: Frame):
    """(flatten fr) — 1×1 frame to a scalar (`AstFlatten.java`)."""
    if fr.nrow != 1 or fr.ncol != 1:
        raise ValueError("flatten requires a 1x1 frame")
    return getrow(fr)[0]


# ---------------------------------------------------------------------------
# time (`prims/time/Ast{AsDate,Week,*TimeZone}.java`)
# ---------------------------------------------------------------------------
_TZ = ["UTC"]  # process-wide like the reference's ParseTime.setTimezone


def _java_fmt_to_strptime(fmt: str) -> str:
    """SimpleDateFormat pattern → strptime (the subset h2o clients use)."""
    out, i = [], 0
    table = [("yyyy", "%Y"), ("yy", "%y"), ("MMM", "%b"), ("MM", "%m"),
             ("dd", "%d"), ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
             ("SSS", "%f")]
    while i < len(fmt):
        for pat, rep in table:
            if fmt.startswith(pat, i):
                out.append(rep)
                i += len(pat)
                break
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


def as_date(v: Vec, fmt: str) -> Vec:
    """(as.Date col format) — parse string/categorical to ms-since-epoch."""
    pyfmt = _java_fmt_to_strptime(fmt)
    if v.is_string():
        vals = v.host_data
    elif v.is_categorical():
        x = v.to_numpy()
        vals = [None if np.isnan(c) else v.domain[int(c)] for c in x]
    else:
        raise ValueError("as.Date expects a string or categorical column")
    out = np.full(v.nrow, np.nan, dtype=np.float64)
    for i, s in enumerate(vals):
        if s is None:
            continue
        try:
            dt = _dt.datetime.strptime(str(s), pyfmt)
            out[i] = dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000.0
        except ValueError:
            pass
    return Vec.from_numpy(out, type=T_TIME)


def week(v: Vec) -> Vec:
    """(week col) — ISO week-of-year from an ms-since-epoch column."""
    ms = v.to_numpy()
    out = np.full(v.nrow, np.nan)
    ok = ~np.isnan(ms)
    days = (ms[ok] / 86400000.0).astype(np.int64)
    dates = np.array(["1970-01-01"], dtype="datetime64[D]")[0] + days
    out[ok] = [float(d.astype(_dt.date).isocalendar()[1]) for d in dates]
    return Vec.from_numpy(out, type=T_INT)


def list_timezones() -> Frame:
    try:
        import zoneinfo
        zones = sorted(zoneinfo.available_timezones())
    except Exception:
        zones = ["UTC"]
    return Frame(["Timezones"], [Vec.from_numpy(np.array(zones, dtype=object))])


def get_timezone() -> Frame:
    return Frame(["Timezone"],
                 [Vec.from_numpy(np.array([_TZ[0]], dtype=object))])


def set_timezone(tz: str) -> None:
    _TZ[0] = str(tz)


# ---------------------------------------------------------------------------
# timeseries (`prims/timeseries/AstIsax.java`)
# ---------------------------------------------------------------------------
def isax(fr: Frame, num_words: int, max_cardinality: int,
         optimize_card: bool = False) -> Frame:
    """(isax fr numWords maxCardinality optimizeCard) — symbolic aggregate
    approximation per row: z-normalize, PAA into num_words means, discretize
    by standard-normal breakpoints into max_cardinality symbols."""
    num_words, max_cardinality = int(num_words), int(max_cardinality)
    if num_words <= 0 or max_cardinality <= 0:
        raise ValueError("numWords and maxCardinality must be greater than 0")
    X = np.asarray(fr.as_matrix())[: fr.nrow]
    mu = np.nanmean(X, axis=1, keepdims=True)
    sd = np.nanstd(X, axis=1, keepdims=True)
    Z = (X - mu) / np.where(sd > 0, sd, 1.0)
    ncol = Z.shape[1]
    # PAA: mean per word over a near-even column partition
    bounds = np.linspace(0, ncol, num_words + 1).astype(int)
    paa = np.stack([np.nanmean(Z[:, bounds[w]:max(bounds[w + 1], bounds[w] + 1)],
                               axis=1)
                    for w in range(num_words)], axis=1)
    # N(0,1) quantile breakpoints, cardinality-1 cuts (Acklam-style inverse
    # via scipy-free erfinv: Φ⁻¹(p) = √2·erfinv(2p−1))
    from math import sqrt
    try:
        from scipy.special import erfinv as _erfinv  # noqa: scipy optional
        cuts = sqrt(2.0) * _erfinv(
            2 * np.arange(1, max_cardinality) / max_cardinality - 1)
    except Exception:
        import torch
        cuts = (sqrt(2.0) * torch.erfinv(torch.tensor(
            2 * np.arange(1, max_cardinality) / max_cardinality - 1))).numpy()
    symbols = np.digitize(paa, cuts)
    names = [f"c{i}" for i in range(num_words)]
    out = Frame(
        ["iSax_index"],
        [Vec.from_numpy(np.array(
            ["_".join(f"{int(s)}^{max_cardinality}" for s in row)
             for row in symbols], dtype=object))])
    for j, n in enumerate(names):
        out.add(n, Vec.from_numpy(symbols[:, j].astype(np.float64)))
    return out


# ---------------------------------------------------------------------------
# tf-idf (`prims/advmath/AstTfIdf.java`)
# ---------------------------------------------------------------------------
def _str_values(v: Vec) -> list:
    if v.is_string():
        return list(v.host_data)
    if v.is_categorical():
        x = v.to_numpy()
        return [None if np.isnan(c) else v.domain[int(c)] for c in x]
    raise ValueError("expected a string/categorical column")


def tf_idf(fr: Frame, doc_id_idx: int, text_idx: int, preprocess: bool = True,
           case_sensitive: bool = True) -> Frame:
    """(tf-idf fr doc_id_idx text_idx preprocess case_sensitive) — output
    [DocID, Word, TF, IDF, TF-IDF]; IDF = log((N+1)/(df+1)) like the
    reference's InverseDocumentFrequencyTask."""
    doc_ids = fr.vec(int(doc_id_idx)).to_numpy()
    texts = _str_values(fr.vec(int(text_idx)))
    pairs: dict[tuple, int] = {}
    docs_of_word: dict[str, set] = {}
    all_docs = set()
    for d, t in zip(doc_ids, texts):
        if t is None or np.isnan(d):
            continue
        d = float(d)
        all_docs.add(d)
        words = str(t).split() if preprocess else [str(t)]
        for w in words:
            if not case_sensitive:
                w = w.lower()
            pairs[(d, w)] = pairs.get((d, w), 0) + 1
            docs_of_word.setdefault(w, set()).add(d)
    N = len(all_docs)
    rows = sorted(pairs.items())
    doc_col = np.array([k[0] for k, _ in rows])
    words = [k[1] for k, _ in rows]
    tf = np.array([c for _, c in rows], dtype=np.float64)
    idf = np.array([np.log((N + 1.0) / (len(docs_of_word[w]) + 1.0))
                    for w in words])
    return Frame(
        ["DocID", "Word", "TF", "IDF", "TF-IDF"],
        [Vec.from_numpy(doc_col),
         Vec.from_numpy(np.array(words, dtype=object)),
         Vec.from_numpy(tf, type=T_INT),
         Vec.from_numpy(idf),
         Vec.from_numpy(tf * idf)])


# ---------------------------------------------------------------------------
# string (`prims/string/AstCountSubstringsWords.java`)
# ---------------------------------------------------------------------------
def num_valid_substrings(v: Vec, words_path: str) -> Vec:
    """(num_valid_substrings col "words_file") — count substrings (len ≥ 2)
    present in the dictionary file."""
    with open(words_path) as f:
        words = set(w.strip() for w in f if w.strip())
    if v.is_string():
        vals = v.host_data
    elif v.is_categorical():
        x = v.to_numpy()
        vals = [None if np.isnan(c) else v.domain[int(c)] for c in x]
    else:
        raise ValueError("num_valid_substrings expects a string column")
    out = np.full(v.nrow, np.nan)
    for i, s in enumerate(vals):
        if s is None:
            continue
        s = str(s)
        out[i] = float(sum(
            1 for a in range(len(s)) for b in range(a + 2, len(s) + 1)
            if s[a:b] in words))
    return Vec.from_numpy(out, type=T_INT)


def grouped_permute(fr: Frame, perm_col: int, gb_cols: list, permute_by: int,
                    keep_col: int) -> Frame:
    """`AstGroupedPermute` — for each group (first groupBy column), pair
    every type-'D' row against every non-'D' row (the permuteBy column's
    domain decides the type, exactly the Java's ``dom[..].equals("D")``):
    amounts (keepCol) sum per distinct permCol id within a type, and the
    output is the per-group cross product [group, In, Out, InAmnt, OutAmnt]
    with In/Out carrying permCol's domain."""
    names = list(fr.names)
    gb = gb_cols[0]
    dom = fr.vec(names[permute_by]).domain
    if not dom:
        raise ValueError("grouped_permute: the permuteBy column must be "
                         "categorical (its domain decides the D/C split)")
    gvals = fr.vec(names[gb]).to_numpy()
    rids = fr.vec(names[perm_col]).to_numpy()
    types = fr.vec(names[permute_by]).to_numpy()
    amnts = fr.vec(names[keep_col]).to_numpy()
    groups: dict = {}
    for i in range(fr.nrow):
        if np.isnan(gvals[i]) or np.isnan(rids[i]):
            continue
        jid = int(gvals[i])
        t = 0 if (not np.isnan(types[i])
                  and dom[int(types[i])] == "D") else 1
        d = groups.setdefault(jid, ({}, {}))[t]
        rid = float(rids[i])
        if rid in d:
            d[rid] += float(amnts[i])
        else:
            d[rid] = float(amnts[i])
    rows = []
    for jid in groups:
        d0, d1 = groups[jid]
        for r0, a0 in d0.items():
            for r1, a1 in d1.items():
                rows.append([float(jid), r0, r1, a0, a1])
    A = (np.array(rows, dtype=np.float64) if rows
         else np.zeros((0, 5), np.float64))
    out_names = [names[gb], "In", "Out", "InAmnt", "OutAmnt"]
    perm_dom = fr.vec(names[perm_col]).domain
    keep_dom = fr.vec(names[keep_col]).domain
    doms = [fr.vec(names[gb]).domain, perm_dom, perm_dom, keep_dom, keep_dom]
    vecs = []
    for j, (nm, dm) in enumerate(zip(out_names, doms)):
        col = A[:, j].astype(np.float32)
        vecs.append(Vec.from_numpy(col, type=T_CAT, domain=list(dm))
                    if dm else Vec.from_numpy(col))
    return Frame(out_names, vecs)
