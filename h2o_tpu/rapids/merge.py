"""Sort & merge — the `water/rapids/RadixOrder.java` / `BinaryMerge.java`
(1,105 LoC) / `Merge.java` analog.

The reference distributes sort/merge with an MSB-radix partition pass, per-MSB
local sorts, and a cluster-wide binary merge. On TPU a multi-column sort is a
device `lexsort` + gather (XLA's sort is already a distributed bitonic/radix
program over the sharded array), and a join is sort + `searchsorted` +
gather-expand — no hand-written partitioning.

merge() mirrors `h2o.merge(x, y, by, all_x, all_y)`: inner/left/right joins on
equal column names, with duplicate-key cartesian expansion (the BinaryMerge
allLeft/allRight semantics).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..frame.frame import Frame
from ..frame.vec import T_STR, Vec
from ..parallel import mesh as meshmod
from ..parallel.mesh import ROWS, shard_map


def sort(fr: Frame, by: list[str] | None = None, ascending: list[bool] | None = None) -> Frame:
    """Row-sort the frame by columns.

    TPU-native: ONE `lax.sort` carries every payload column through the sort
    network alongside the keys, so no post-sort permutation gather is needed
    (a 100M-row dynamic gather costs more than the sort itself on TPU).
    String columns still need the permutation host-side; the sort emits it as
    a carried iota only when one exists."""
    by = by or fr.names
    ascending = ascending or [True] * len(by)
    n = fr.nrow
    plen = fr.vec(by[0]).plen
    # primary key first in lax.sort; NaNs first ascending (reference order),
    # padding rows always last
    pad = (jnp.arange(plen) >= n).astype(jnp.float32)
    keys = [pad]
    for b, asc in zip(by, ascending):
        k = fr.vec(b).data[:]
        k = jnp.where(jnp.isnan(k), -jnp.inf, k)
        keys.append(k if asc else -k)
    num_names = [nm for nm in fr.names if not fr.vec(nm).is_string()]
    str_names = [nm for nm in fr.names if fr.vec(nm).is_string()]
    payload = [fr.vec(nm).data for nm in num_names]
    if str_names:
        payload.append(jnp.arange(plen, dtype=jnp.int32))  # permutation
    sorted_all = jax.lax.sort(tuple(keys) + tuple(payload),
                              num_keys=len(keys), is_stable=True)
    out_cols = sorted_all[len(keys):]
    names, vecs = [], []
    perm = (np.asarray(out_cols[-1])[:n] if str_names else None)
    for nm in fr.names:
        v = fr.vec(nm)
        if v.is_string():
            vecs.append(Vec(None, n, type=T_STR, host_data=v.host_data[perm]))
        else:
            vecs.append(Vec.from_device(out_cols[num_names.index(nm)], n,
                                        type=v.type, domain=v.domain))
        names.append(nm)
    return Frame(names, vecs)


def _gather(fr: Frame, idx, nrow: int) -> Frame:
    names, vecs = [], []
    for name in fr.names:
        v = fr.vec(name)
        if v.is_string():
            host_idx = np.asarray(idx)[:nrow]
            vecs.append(Vec(None, nrow, type=T_STR,
                            host_data=v.host_data[host_idx]))
        else:
            vecs.append(Vec.from_device(v.data[idx], nrow, type=v.type,
                                        domain=v.domain))
        names.append(name)
    return Frame(names, vecs)


@functools.partial(jax.jit, static_argnames=("all_x",))
def _merge_ranges(lk, rk, r_payload, all_x: bool):
    """Phase 1 (one program): sort-carry the right table + match ranges.

    Match ranges come from ONE combined stable sort of [right keys ∥ left
    keys] plus piecewise-constant Δ-cumsum fills — NOT searchsorted: binary
    search costs ~2·log2(rn) dependent gathers per left row on TPU (the
    measured 30s+ of a 100M×1M merge); the combined sort rides the same
    bandwidth-bound sort network as everything else. Stability puts equal
    right keys BEFORE the left element, so the running right-count at a left
    position is `hi`; `lo = hi − run-length of the matching right key`.
    """
    rn = rk.shape[0]
    ln = lk.shape[0]
    srt = jax.lax.sort((rk,) + tuple(r_payload), num_keys=1, is_stable=True)
    rk_s, r_cols_s = srt[0], srt[1:]

    combined = jnp.concatenate([rk_s, lk])
    ids = jnp.arange(rn + ln, dtype=jnp.int32)  # right block first
    ck, ci = jax.lax.sort((combined, ids), num_keys=1, is_stable=True)
    is_right = ci < rn

    # combined positions of the right rows, in j order (is_right is True at
    # exactly rn positions)
    pos = jnp.nonzero(is_right, size=rn, fill_value=rn + ln - 1)[0]

    def fill_at_right(vals_r, dtype=jnp.int32):
        """Piecewise-constant forward fill of per-right-row values over the
        combined order (value changes only at right positions): scatter the
        per-row Δs at `pos`, cumsum, and shift by vals_r[0] from pos[0] on
        (before the first right position the fill reads 0 — callers gate on
        hi_fill > 0)."""
        delta = jnp.diff(vals_r, prepend=vals_r[:1])  # delta[0] == 0
        buf = jnp.zeros(rn + ln, dtype).at[pos].add(delta, mode="drop")
        filled = jnp.cumsum(buf)
        base = (jnp.arange(rn + ln) >= pos[0]).astype(dtype) * vals_r[0]
        return filled + base

    hi_fill = jnp.cumsum(is_right.astype(jnp.int32))  # right ≤ position
    rk_bits = jax.lax.bitcast_convert_type(rk_s, jnp.int32)
    prevkey_fill = fill_at_right(rk_bits)
    # run starts within the sorted right keys (1M-scale host of the fill)
    newrun = jnp.concatenate([jnp.ones(1, jnp.int32),
                              (rk_s[1:] != rk_s[:-1]).astype(jnp.int32)])
    run_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(newrun > 0, jnp.arange(rn, dtype=jnp.int32),
                               0))
    runstart_fill = fill_at_right(run_start)

    ck_bits = jax.lax.bitcast_convert_type(ck, jnp.int32)
    matched = (prevkey_fill == ck_bits) & (hi_fill > 0)
    mult = jnp.where(matched, hi_fill - 1 - runstart_fill + 1, 0)
    # carry per-position (hi, mult) back to original left order: payload ids
    # are 0..rn+ln-1, so one more sort by id is an exact inverse permutation
    _, hi_back, mult_back = jax.lax.sort(
        (ci, hi_fill, mult), num_keys=1, is_stable=True)
    hi_l = hi_back[rn:]
    counts = mult_back[rn:]
    lo = hi_l - counts
    counts_eff = jnp.maximum(counts, 1) if all_x else counts
    return r_cols_s, lo, counts, jnp.cumsum(counts_eff)


@functools.partial(jax.jit, static_argnames=("total",))
def _merge_expand(l_cols, r_cols_s, lo, counts, cum, total: int):
    """Phase 2 (one program, output shape fixed by `total`): duplicate-key
    expansion via scatter + cumsum of per-segment DELTAS — binary search
    (searchsorted) over the cumsum is gather-bound on TPU (~27 dependent
    gathers per row); delta-cumsum replaces it with one scatter pass and
    bandwidth-bound scans. Segment starts are in left-row order, so every
    per-row quantity q[l_idx] materializes as cumsum(scatter(Δq at starts))."""
    starts = jnp.concatenate([jnp.zeros(1, cum.dtype), cum[:-1]])

    def fill(per_row):  # per-left-row values -> per-output-row via Δ-cumsum
        delta = jnp.diff(per_row, prepend=per_row[:1])
        buf = jnp.zeros(total, per_row.dtype).at[starts].add(delta, mode='drop')
        buf = buf.at[0].add(per_row[0])
        return jnp.cumsum(buf)

    row_start = fill(starts)
    row_lo = fill(lo)
    row_matched = fill((counts > 0).astype(jnp.int32)) > 0
    within = jnp.arange(total) - row_start
    rn = r_cols_s[0].shape[0] if r_cols_s else 1
    r_srt_pos = jnp.clip(row_lo + within, 0, rn - 1)

    def fill_f32(col):
        # left-side gathers are MONOTONE (output keeps left-row order), so a
        # 100M-row dynamic gather per column is replaced by the same Δ-cumsum
        # expansion applied to the column's raw int32 bit pattern — int32
        # adds wrap mod 2^32, so diff→scatter→cumsum reconstructs the bits
        # EXACTLY (no float rounding), at scan bandwidth instead of TPU
        # serial-gather throughput.
        bits = jax.lax.bitcast_convert_type(col.astype(jnp.float32),
                                            jnp.int32)
        return jax.lax.bitcast_convert_type(fill(bits), jnp.float32)

    out_l = tuple(fill_f32(c) for c in l_cols)

    # Right-side values: out_r[i] = c[r_srt_pos[i]] with arbitrary (NOT
    # monotone) positions. A 100M-row dynamic gather is the old 30s+ cost;
    # instead gather-via-sort, all bandwidth-bound ops:
    #   1. sort (pos, output-row-id) — groups outputs by right row;
    #   2. per right row j, occurrence counts from searchsorted boundaries
    #      (rn log-total probes, tiny);
    #   3. repeat each c[j] occ[j] times = piecewise-constant Δ-cumsum on
    #      raw bits (exact);
    #   4. one sort back by output-row-id carrying all expanded columns.
    if r_cols_s:
        rn_i = r_cols_s[0].shape[0]
        pos_s, i_s = jax.lax.sort(
            (r_srt_pos, jnp.arange(total, dtype=jnp.int32)),
            num_keys=1, is_stable=True)
        bounds = jnp.searchsorted(pos_s, jnp.arange(rn_i + 1,
                                                    dtype=jnp.int32))
        occ_starts = bounds[:-1]  # first output slot of right row j

        def repeat_bits(c):
            bits = jax.lax.bitcast_convert_type(c.astype(jnp.float32),
                                                jnp.int32)
            delta = jnp.diff(bits, prepend=bits[:1])
            buf = jnp.zeros(total, jnp.int32).at[occ_starts].add(
                delta, mode="drop")
            buf = buf.at[0].add(bits[0] - delta[0])
            expanded = jnp.cumsum(buf)
            return jax.lax.bitcast_convert_type(expanded, jnp.float32)

        expanded = tuple(repeat_bits(c) for c in r_cols_s)
        unsorted = jax.lax.sort((i_s,) + expanded, num_keys=1,
                                is_stable=True)[1:]
        out_r = tuple(jnp.where(row_matched, c, jnp.nan) for c in unsorted)
    else:
        out_r = ()
    return out_l, out_r


#: compiled sharded-expand programs keyed by (mesh, total, plen, n_l, n_r) —
#: merges are host-driven and rare, but a grid of same-shape joins (CV fold
#: assembly) should not re-trace per call
_EXPAND_PROGS: dict = {}


def _sharded_expand_program(mesh, total: int, plen: int, n_l: int, n_r: int):
    """Phase 2 as explicit per-shard work inside ``shard_map`` — the fix for
    the jax-0.4.x GSPMD mis-partition that kept this phase pinned replicated
    since PR 1 (GSPMD computed the Δ-scatter + cumsum fills per-shard on
    row-sharded operands, so outputs diverged at the first shard boundary).

    The key structural fact: every phase-2 output row depends only on the
    PRE-expansion tables (per-left-row ``lo``/``counts``/``cum`` and the
    sorted right payload — ln/rn-sized, replicated like the pinned path
    already held them), never on other output rows. So each shard of the
    ``rows`` axis computes exactly its own ``L = plen / n_shards`` slice of
    the (possibly cartesian-expanded, ≫ ln) output with offset-aware fills:

    - the global ``cumsum(scatter(Δ at starts))`` fill at positions
      [off, off+L) equals ``Σ Δ[starts < off]  +  local-cumsum of the Δs
      landing inside the shard`` — int32 adds wrap mod 2³², so the split
      sum is BIT-exact against the replicated oracle regardless of order;
    - the gather-via-sort right-side expansion is slot-local: sorting the
      shard's own (pos, slot) pairs and repeating each right row's bits
      over its local occupancy assigns every slot ``c[pos[slot]]`` exactly,
      independent of what other shards hold.

    Outputs land row-sharded (``P(ROWS)``, padded to ``plen`` with NaN
    tails per the Vec padding convention) — per-chip output HBM drops to
    ~1/n_shards where the pinned path replicated the whole expansion.
    ``tests/test_sharded_frames.py`` pins the sharded output bit-equal to
    the replicated oracle; ``H2O_TPU_SHARDED_MERGE=0`` reverts."""
    key = (mesh, total, plen, n_l, n_r)
    hit = _EXPAND_PROGS.get(key)
    if hit is not None:
        return hit
    shards = mesh.shape[ROWS]
    L = plen // shards

    def spmd(l_cols, r_cols_s, lo, counts, cum):
        off = jax.lax.axis_index(ROWS).astype(jnp.int32) * L
        rowid = off + jnp.arange(L, dtype=jnp.int32)
        starts = jnp.concatenate([jnp.zeros(1, cum.dtype), cum[:-1]])

        def fill(per_row):
            # the shard's window of the global Δ-scatter + cumsum: deltas
            # before the window contribute a scalar base (order-free int32
            # wrap-around sum — exact), deltas inside it scatter locally
            delta = jnp.diff(per_row, prepend=per_row[:1])
            inside = (starts >= off) & (starts < off + L)
            idx = jnp.clip(starts - off, 0, L - 1)
            buf = jnp.zeros(L, per_row.dtype).at[idx].add(
                jnp.where(inside, delta, jnp.zeros_like(delta)))
            base = jnp.sum(jnp.where(starts < off, delta,
                                     jnp.zeros_like(delta))) + per_row[0]
            return jnp.cumsum(buf) + base

        row_start = fill(starts)
        row_lo = fill(lo)
        row_matched = fill((counts > 0).astype(jnp.int32)) > 0
        within = rowid - row_start
        rn = r_cols_s[0].shape[0] if r_cols_s else 1
        r_srt_pos = jnp.clip(row_lo + within, 0, rn - 1)
        valid = rowid < total  # padding tail rows -> NaN (Vec convention)

        def fill_f32(col):
            bits = jax.lax.bitcast_convert_type(col.astype(jnp.float32),
                                                jnp.int32)
            return jax.lax.bitcast_convert_type(fill(bits), jnp.float32)

        out_l = tuple(jnp.where(valid, fill_f32(c), jnp.nan)
                      for c in l_cols)

        if r_cols_s:
            # gather-via-sort over the SHARD's slots (same bandwidth-bound
            # construction as the oracle, applied to the local slice):
            # sort (pos, slot), per-right-row occupancy from searchsorted
            # bounds, repeat each c[j]'s bits over its occupancy, sort back
            rn_i = r_cols_s[0].shape[0]
            pos_s, i_s = jax.lax.sort(
                (r_srt_pos, jnp.arange(L, dtype=jnp.int32)),
                num_keys=1, is_stable=True)
            bounds = jnp.searchsorted(pos_s,
                                      jnp.arange(rn_i + 1, dtype=jnp.int32))
            occ_starts = bounds[:-1]

            def repeat_bits(c):
                bits = jax.lax.bitcast_convert_type(c.astype(jnp.float32),
                                                    jnp.int32)
                delta = jnp.diff(bits, prepend=bits[:1])
                buf = jnp.zeros(L, jnp.int32).at[occ_starts].add(
                    delta, mode="drop")
                buf = buf.at[0].add(bits[0] - delta[0])
                return jax.lax.bitcast_convert_type(jnp.cumsum(buf),
                                                    jnp.float32)

            expanded = tuple(repeat_bits(c) for c in r_cols_s)
            unsorted = jax.lax.sort((i_s,) + expanded, num_keys=1,
                                    is_stable=True)[1:]
            out_r = tuple(jnp.where(valid & row_matched, c, jnp.nan)
                          for c in unsorted)
        else:
            out_r = ()
        return out_l, out_r

    in_specs = ((P(),) * n_l, (P(),) * n_r, P(), P(), P())
    out_specs = ((P(ROWS),) * n_l, (P(ROWS),) * n_r)
    prog = jax.jit(shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))
    _EXPAND_PROGS[key] = prog
    return prog


def _merge_schema(left: Frame, right: Frame, key: str) -> list:
    """Output column order + source Vec (the type/domain carrier): all
    left columns, then right's non-key columns — ONE schema shared by the
    zero-match short-circuit and the expansion tail."""
    return ([(n, left.vec(n)) for n in left.names]
            + [(n, right.vec(n)) for n in right.names if n != key])


def _merge_device(left: Frame, right: Frame, key: str, all_x: bool) -> Frame:
    """Single-key numeric join on device in TWO compiled programs (the host
    sync between them fixes the data-dependent output size). No per-row host
    work — the RadixOrder/BinaryMerge role collapsed into XLA sorts and
    Δ-cumsum fills (gather-free)."""
    ln, rn = left.nrow, right.nrow
    # NA keys never match (BinaryMerge semantics): +inf left vs -inf right.
    # Zeros canonicalize (+0.0 == -0.0 must JOIN): the range matcher compares
    # raw bit patterns, and 0x0 != 0x80000000.
    lk = left.vec(key).data[:ln]
    lk = jnp.where(jnp.isnan(lk), jnp.inf, jnp.where(lk == 0, 0.0, lk))
    rk = right.vec(key).data[:rn]
    rk = jnp.where(jnp.isnan(rk), -jnp.inf, jnp.where(rk == 0, 0.0, rk))
    r_payload = tuple(right.vec(n).data[:rn] for n in right.names if n != key)
    r_cols_s, lo, counts, cum = _merge_ranges(lk, rk, r_payload, all_x)
    total = int(cum[-1])  # the one host sync
    sch = _merge_schema(left, right, key)
    if total == 0:
        # zero matches (inner join, disjoint keys): phase 2's fills assume
        # ≥1 output row (`buf.at[0]`), so build the empty frame directly
        return Frame([n for n, _ in sch],
                     [Vec.from_numpy(np.zeros(0, np.float32), type=v.type,
                                     domain=v.domain) for _, v in sch])
    l_cols = tuple(left.vec(n).data[:ln] for n in left.names)
    # Phase 2's Δ-scatter + cumsum fills are exact only over the whole
    # array, and the jax-0.4.x GSPMD partitioner computes them per-shard on
    # row-sharded operands (outputs diverge at the first shard boundary —
    # caught by __graft_entry__'s multichip dry run). The production path
    # therefore runs the fills as EXPLICIT per-shard work inside shard_map
    # (`_sharded_expand_program`): pre-expansion inputs replicated, the
    # expanded output row-sharded. `_merge_expand` stays as the replicated
    # ORACLE the sharded output is bit-parity-pinned against
    # (H2O_TPU_SHARDED_MERGE=0 reverts to it; single-row-shard meshes take
    # it too — replication is a no-op there).
    from ..utils import knobs

    mesh = meshmod.default_mesh()
    if (meshmod.n_row_shards(mesh) > 1
            and knobs.get_bool("H2O_TPU_SHARDED_MERGE")):
        plen = meshmod.padded_len(total, mesh)
        prog = _sharded_expand_program(mesh, total, plen, len(l_cols),
                                       len(r_cols_s))
        out_l, out_r = prog(l_cols, r_cols_s, lo, counts, cum)
    else:
        put = lambda t: tuple(meshmod.put_replicated(c, mesh) for c in t)
        out_l, out_r = _merge_expand(put(l_cols), put(r_cols_s),
                                     meshmod.put_replicated(lo, mesh),
                                     meshmod.put_replicated(counts, mesh),
                                     meshmod.put_replicated(cum, mesh),
                                     total)

    return Frame([n for n, _ in sch],
                 [Vec.from_device(col, total, type=v.type, domain=v.domain)
                  for (_, v), col in zip(sch, out_l + out_r)])


def merge(left: Frame, right: Frame, by: list[str] | None = None,
          all_x: bool = False, all_y: bool = False) -> Frame:
    """Join on shared key columns. Single-key numeric joins run fully on
    device (_merge_device); multi-key / string / right-outer joins take the
    host radix path. Duplicate right keys expand cartesian-style like
    BinaryMerge."""
    by = by or [n for n in left.names if n in right.names]
    if not by:
        raise ValueError("no common columns to merge on")
    if (len(by) == 1 and not all_y
            and not any(left.vec(n).is_string() for n in left.names)
            and not any(right.vec(n).is_string() for n in right.names)
            # exact_data = f32-lossy values (big int64/time keys): the device
            # columns are projections, so joining on them would collide
            # distinct keys — those frames take the exact host path
            and not any(left.vec(n).exact_data is not None
                        for n in left.names)
            and not any(right.vec(n).exact_data is not None
                        for n in right.names)
            and not left.vec(by[0]).is_categorical()
            and not right.vec(by[0]).is_categorical()
            # empty tables take the host path: the combined-sort fills in
            # _merge_ranges/_merge_expand assume rn >= 1
            and left.nrow > 0 and right.nrow > 0):
        return _merge_device(left, right, by[0], all_x)
    ln, rn = left.nrow, right.nrow
    # NA keys never match (BinaryMerge semantics): NaN -> +inf on the left,
    # -inf on the right, so searchsorted ranges for them are always empty.
    lk = np.stack([np.where(np.isnan(c), np.inf, c) for c in
                   (left.vec(b).to_numpy() for b in by)], axis=1)
    rk = np.stack([np.where(np.isnan(c), -np.inf, c) for c in
                   (right.vec(b).to_numpy() for b in by)], axis=1)
    # categorical codes must be aligned by LEVEL NAME, not code
    for j, b in enumerate(by):
        lv, rv = left.vec(b), right.vec(b)
        if lv.is_categorical() and rv.domain != lv.domain and rv.domain:
            remap = {lvl: i for i, lvl in enumerate(lv.domain)}
            rk[:, j] = np.array([remap.get(rv.domain[int(c)], -np.inf)
                                 if np.isfinite(c) else c for c in rk[:, j]])

    from ..backend.native import radix_lexsort

    # native parallel radix (RadixOrder/BinaryMerge's role) above the
    # size threshold; np.lexsort below it
    r_order = radix_lexsort([rk[:, j] for j in range(rk.shape[1])])
    rk_s = rk[r_order]

    # for each left row: range of matching right rows in sorted order
    lo = _searchsorted_rows(rk_s, lk, "left")
    hi = _searchsorted_rows(rk_s, lk, "right")
    counts = hi - lo
    matched = counts > 0

    # vectorized cartesian expansion (no per-row python): each left row i
    # yields counts_eff[i] output rows; matched rows enumerate their sorted
    # right range, unmatched all_x rows get one row with r_pos = -1
    counts_eff = np.maximum(counts, 1) if all_x else counts
    l_idx = np.repeat(np.arange(ln), counts_eff)
    tot = int(counts_eff.sum())
    block_start = np.cumsum(counts_eff) - counts_eff
    offs = np.arange(tot) - np.repeat(block_start, counts_eff)
    srt_pos = np.repeat(lo, counts_eff) + offs
    row_matched = np.repeat(matched, counts_eff)
    if rn:
        r_pos = np.where(row_matched, r_order[np.clip(srt_pos, 0, rn - 1)], -1)
    else:
        r_pos = np.full(tot, -1, dtype=np.int64)
    if all_y:
        used = np.zeros(rn, dtype=bool)
        used[r_pos[r_pos >= 0]] = True
        extra = np.where(~used)[0]
        l_idx = np.concatenate([l_idx, np.full(len(extra), -1)])
        r_pos = np.concatenate([r_pos, extra])

    out_names, out_vecs = [], []
    for j, name in enumerate(left.names):
        v = left.vec(name)
        if name in by and all_y:
            # key columns: unmatched right rows contribute their key value,
            # already remapped into LEFT-domain code space in rk (±inf = no
            # left-space equivalent -> NA)
            bj = by.index(name)
            lhost = v.to_numpy()
            fill = np.where(np.isfinite(rk[:, bj]), rk[:, bj], np.nan)
            fill_at = (fill[np.clip(r_pos, 0, None)] if rn
                       else np.full(len(r_pos), np.nan))
            lvals = (lhost[np.clip(l_idx, 0, None)] if ln
                     else np.full(len(l_idx), np.nan))
            out = np.where(l_idx >= 0, lvals, fill_at)
            col = Vec.from_numpy(out.astype(np.float32), type=v.type,
                                 domain=v.domain)
        else:
            col = _take(v, l_idx)
        out_names.append(name)
        out_vecs.append(col)
    for name in right.names:
        if name in by:
            continue
        out_names.append(name)
        out_vecs.append(_take(right.vec(name), r_pos))
    return Frame(out_names, out_vecs)


def _searchsorted_rows(sorted_rows: np.ndarray, queries: np.ndarray, side):
    """Row-wise (lexicographic) searchsorted via structured-array view."""
    def view(a):
        a = np.ascontiguousarray(a)
        return a.view([("", a.dtype)] * a.shape[1]).ravel()

    return np.searchsorted(view(sorted_rows), view(queries), side=side)


def _take(v: Vec, idx: np.ndarray):
    """Gather host rows by index; idx < 0 -> NA (unmatched outer-join rows)."""
    host = v.to_numpy()
    if v.is_string():
        out = np.array([host[i] if i >= 0 else None for i in idx], dtype=object)
        return Vec(None, len(idx), type=T_STR, host_data=out)
    if len(host) == 0:
        out = np.full(len(idx), np.nan)
    else:
        out = np.where(idx >= 0, host[np.clip(idx, 0, None)], np.nan)
    return Vec.from_numpy(out.astype(np.float32), type=v.type, domain=v.domain)
