"""Sort & merge — the `water/rapids/RadixOrder.java` / `BinaryMerge.java`
(1,105 LoC) / `Merge.java` analog.

The reference distributes sort/merge with an MSB-radix partition pass, per-MSB
local sorts, and a cluster-wide binary merge. On TPU a multi-column sort is a
device `lexsort` + gather (XLA's sort is already a distributed bitonic/radix
program over the sharded array), and a join is sort + `searchsorted` +
gather-expand — no hand-written partitioning.

merge() mirrors `h2o.merge(x, y, by, all_x, all_y)`: inner/left/right joins on
equal column names, with duplicate-key cartesian expansion (the BinaryMerge
allLeft/allRight semantics).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_STR, Vec


def sort(fr: Frame, by: list[str] | None = None, ascending: list[bool] | None = None) -> Frame:
    """Row-sort the frame by columns (device lexsort + gather)."""
    by = by or fr.names
    ascending = ascending or [True] * len(by)
    n = fr.nrow
    # lexsort: last key is primary -> reverse; NaNs sort last (H2O sorts NAs first
    # for ascending — match that by mapping NaN to -inf/. +inf for desc)
    keys = []
    for b, asc in zip(reversed(by), reversed(ascending)):
        k = fr.vec(b).data[:]
        k = jnp.where(jnp.isnan(k), -jnp.inf, k)  # NAs first (reference order)
        keys.append(k if asc else -k)
    # padding rows must sort last regardless; lexsort's LAST key is primary
    pad = (jnp.arange(fr.vec(by[0]).plen) >= n).astype(jnp.float32)
    keys.append(pad)
    order = jnp.lexsort(keys)
    return _gather(fr, order, n)


def _gather(fr: Frame, idx, nrow: int) -> Frame:
    names, vecs = [], []
    for name in fr.names:
        v = fr.vec(name)
        if v.is_string():
            host_idx = np.asarray(idx)[:nrow]
            vecs.append(Vec(None, nrow, type=T_STR,
                            host_data=v.host_data[host_idx]))
        else:
            vecs.append(Vec.from_device(v.data[idx], nrow, type=v.type,
                                        domain=v.domain))
        names.append(name)
    return Frame(names, vecs)


def merge(left: Frame, right: Frame, by: list[str] | None = None,
          all_x: bool = False, all_y: bool = False) -> Frame:
    """Join on shared key columns. Host orchestration of device sorts;
    duplicate right keys expand cartesian-style like BinaryMerge."""
    by = by or [n for n in left.names if n in right.names]
    if not by:
        raise ValueError("no common columns to merge on")
    ln, rn = left.nrow, right.nrow
    # NA keys never match (BinaryMerge semantics): NaN -> +inf on the left,
    # -inf on the right, so searchsorted ranges for them are always empty.
    lk = np.stack([np.where(np.isnan(c), np.inf, c) for c in
                   (left.vec(b).to_numpy() for b in by)], axis=1)
    rk = np.stack([np.where(np.isnan(c), -np.inf, c) for c in
                   (right.vec(b).to_numpy() for b in by)], axis=1)
    # categorical codes must be aligned by LEVEL NAME, not code
    for j, b in enumerate(by):
        lv, rv = left.vec(b), right.vec(b)
        if lv.is_categorical() and rv.domain != lv.domain and rv.domain:
            remap = {lvl: i for i, lvl in enumerate(lv.domain)}
            rk[:, j] = np.array([remap.get(rv.domain[int(c)], -np.inf)
                                 if np.isfinite(c) else c for c in rk[:, j]])

    from ..backend.native import radix_lexsort

    # native parallel radix (RadixOrder/BinaryMerge's role) above the
    # size threshold; np.lexsort below it
    r_order = radix_lexsort([rk[:, j] for j in range(rk.shape[1])])
    rk_s = rk[r_order]

    # for each left row: range of matching right rows in sorted order
    lo = _searchsorted_rows(rk_s, lk, "left")
    hi = _searchsorted_rows(rk_s, lk, "right")
    counts = hi - lo
    matched = counts > 0

    # vectorized cartesian expansion (no per-row python): each left row i
    # yields counts_eff[i] output rows; matched rows enumerate their sorted
    # right range, unmatched all_x rows get one row with r_pos = -1
    counts_eff = np.maximum(counts, 1) if all_x else counts
    l_idx = np.repeat(np.arange(ln), counts_eff)
    tot = int(counts_eff.sum())
    block_start = np.cumsum(counts_eff) - counts_eff
    offs = np.arange(tot) - np.repeat(block_start, counts_eff)
    srt_pos = np.repeat(lo, counts_eff) + offs
    row_matched = np.repeat(matched, counts_eff)
    if rn:
        r_pos = np.where(row_matched, r_order[np.clip(srt_pos, 0, rn - 1)], -1)
    else:
        r_pos = np.full(tot, -1, dtype=np.int64)
    if all_y:
        used = np.zeros(rn, dtype=bool)
        used[r_pos[r_pos >= 0]] = True
        extra = np.where(~used)[0]
        l_idx = np.concatenate([l_idx, np.full(len(extra), -1)])
        r_pos = np.concatenate([r_pos, extra])

    out_names, out_vecs = [], []
    for j, name in enumerate(left.names):
        v = left.vec(name)
        if name in by and all_y:
            # key columns: unmatched right rows contribute their key value,
            # already remapped into LEFT-domain code space in rk (±inf = no
            # left-space equivalent -> NA)
            bj = by.index(name)
            lhost = v.to_numpy()
            fill = np.where(np.isfinite(rk[:, bj]), rk[:, bj], np.nan)
            out = np.where(l_idx >= 0, lhost[np.clip(l_idx, 0, None)],
                           fill[np.clip(r_pos, 0, None)])
            col = Vec.from_numpy(out.astype(np.float32), type=v.type,
                                 domain=v.domain)
        else:
            col = _take(v, l_idx)
        out_names.append(name)
        out_vecs.append(col)
    for name in right.names:
        if name in by:
            continue
        out_names.append(name)
        out_vecs.append(_take(right.vec(name), r_pos))
    return Frame(out_names, out_vecs)


def _searchsorted_rows(sorted_rows: np.ndarray, queries: np.ndarray, side):
    """Row-wise (lexicographic) searchsorted via structured-array view."""
    def view(a):
        a = np.ascontiguousarray(a)
        return a.view([("", a.dtype)] * a.shape[1]).ravel()

    return np.searchsorted(view(sorted_rows), view(queries), side=side)


def _take(v: Vec, idx: np.ndarray):
    """Gather host rows by index; idx < 0 -> NA (unmatched outer-join rows)."""
    host = v.to_numpy()
    if v.is_string():
        out = np.array([host[i] if i >= 0 else None for i in idx], dtype=object)
        return Vec(None, len(idx), type=T_STR, host_data=out)
    out = np.where(idx >= 0, host[np.clip(idx, 0, None)], np.nan)
    return Vec.from_numpy(out.astype(np.float32), type=v.type, domain=v.domain)
