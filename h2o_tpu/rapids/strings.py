"""String ops — `water/rapids/ast/prims/string/` analog (toupper, tolower,
sub/gsub, trim, strsplit, nchar, substring, grep/countmatches, replaceall...).

String Vecs live host-side (variable-length data has no place in HBM —
SURVEY.md §7.2); these ops are vectorized numpy-object passes. Categorical
Vecs get the op applied to their DOMAIN only (the reference does exactly this:
string ops on enums rewrite the domain, `AstToUpper` etc.), which is O(levels)
instead of O(rows) — the win of the domain representation.
"""

from __future__ import annotations

import re

import numpy as np

from ..frame.vec import T_CAT, T_INT, T_STR, Vec


def _host_strings(v: Vec) -> list:
    """Row-wise python strings (None = NA) from a string or categorical Vec."""
    if v.is_string():
        return list(v.host_data)
    if v.is_categorical():
        x = v.to_numpy()
        return [None if np.isnan(c) else v.domain[int(c)] for c in x]
    raise TypeError(f"string op on {v.type} Vec")


def _apply(v: Vec, fn) -> Vec:
    if v.is_categorical():
        return Vec(v.data, v.nrow, type=T_CAT,
                   domain=[fn(d) for d in v.domain])
    if not v.is_string():
        raise TypeError(f"string op on {v.type} Vec")
    out = np.array([None if s is None else fn(str(s)) for s in v.host_data],
                   dtype=object)
    return Vec(None, v.nrow, type=T_STR, host_data=out)


def toupper(v): return _apply(v, str.upper)
def tolower(v): return _apply(v, str.lower)
def trim(v): return _apply(v, str.strip)
def lstrip(v, chars=None): return _apply(v, lambda s: s.lstrip(chars))
def rstrip(v, chars=None): return _apply(v, lambda s: s.rstrip(chars))


def sub(v, pattern, replacement, ignore_case=False):
    flags = re.IGNORECASE if ignore_case else 0
    rx = re.compile(pattern, flags)
    return _apply(v, lambda s: rx.sub(replacement, s, count=1))


def gsub(v, pattern, replacement, ignore_case=False):
    flags = re.IGNORECASE if ignore_case else 0
    rx = re.compile(pattern, flags)
    return _apply(v, lambda s: rx.sub(replacement, s))


def substring(v, start, end=None):
    return _apply(v, lambda s: s[start:end])


def replaceall(v, pattern, replacement):  # alias used by h2o-py
    return gsub(v, pattern, replacement)


def nchar(v: Vec) -> Vec:
    if v.is_categorical():
        lens = np.array([len(d) for d in v.domain], dtype=np.float32)
        host = v.to_numpy()
        out = np.full(host.shape, np.nan, dtype=np.float32)
        ok = ~np.isnan(host)
        out[ok] = lens[host[ok].astype(np.int64)]
        return Vec.from_numpy(out, type=T_INT)
    out = np.array([np.nan if s is None else float(len(str(s)))
                    for s in v.host_data], dtype=np.float32)
    return Vec.from_numpy(out, type=T_INT)


def countmatches(v: Vec, patterns) -> Vec:
    if isinstance(patterns, str):
        patterns = [patterns]
    rxs = [re.compile(p) for p in patterns]

    def cnt(s):
        return float(sum(len(r.findall(s)) for r in rxs))

    if v.is_categorical():
        per_level = np.array([cnt(d) for d in v.domain], dtype=np.float32)
        host = v.to_numpy()
        out = np.full(host.shape, np.nan, dtype=np.float32)
        ok = ~np.isnan(host)
        out[ok] = per_level[host[ok].astype(np.int64)]
        return Vec.from_numpy(out, type=T_INT)
    out = np.array([np.nan if s is None else cnt(str(s)) for s in v.host_data],
                   dtype=np.float32)
    return Vec.from_numpy(out, type=T_INT)


def grep(v: Vec, pattern, ignore_case=False, invert=False, output_logical=True) -> Vec:
    """`AstGrep` — logical (or index) match vector over a string/cat column."""
    flags = re.IGNORECASE if ignore_case else 0
    rx = re.compile(pattern, flags)

    def hit(s):
        return rx.search(s) is not None

    if v.is_categorical():
        per_level = np.array([hit(d) for d in v.domain])
        host = v.to_numpy()
        ok = ~np.isnan(host)
        m = np.zeros(host.shape, dtype=bool)
        m[ok] = per_level[host[ok].astype(np.int64)]
    else:
        m = np.array([False if s is None else hit(str(s)) for s in v.host_data])
    if invert:
        m = ~m
    if output_logical:
        return Vec.from_numpy(m.astype(np.float32), type=T_INT)
    return Vec.from_numpy(np.where(m)[0].astype(np.float32), type=T_INT)


def strsplit(v: Vec, pattern) -> list[Vec]:
    """Split into N string columns (ragged padded with None) — `AstStrSplit`."""
    if v.is_categorical():
        host = np.array([None if np.isnan(c) else v.domain[int(c)]
                         for c in v.to_numpy()], dtype=object)
    else:
        host = v.host_data
    rx = re.compile(pattern)
    parts = [None if s is None else rx.split(str(s)) for s in host]
    width = max((len(p) for p in parts if p), default=0)
    cols = []
    for j in range(width):
        cols.append(Vec(None, v.nrow, type=T_STR, host_data=np.array(
            [p[j] if p and j < len(p) else None for p in parts], dtype=object)))
    return cols


def ascharacter(v: Vec) -> Vec:
    """enum -> string column."""
    host = v.to_numpy()
    out = np.array([None if np.isnan(c) else v.domain[int(c)] for c in host],
                   dtype=object)
    return Vec(None, v.nrow, type=T_STR, host_data=out)


def asfactor(v: Vec) -> Vec:
    """string/int -> enum (sorted-domain interning, ParseDataset analog)."""
    if v.is_categorical():
        return v
    if v.is_string():
        vals = [None if s is None else str(s) for s in v.host_data]
        dom = sorted({s for s in vals if s is not None})
        lookup = {d: i for i, d in enumerate(dom)}
        codes = np.array([np.nan if s is None else lookup[s] for s in vals],
                         dtype=np.float32)
        return Vec.from_numpy(codes, type=T_CAT, domain=dom)
    host = v.to_numpy()
    ok = ~np.isnan(host)
    lv = np.unique(host[ok]).astype(np.int64)
    lookup = {x: i for i, x in enumerate(lv)}
    codes = np.full(host.shape, np.nan, dtype=np.float32)
    codes[ok] = [lookup[int(x)] for x in host[ok]]
    return Vec.from_numpy(codes, type=T_CAT, domain=[str(x) for x in lv])


def entropy(v: Vec) -> Vec:
    """Per-string Shannon character entropy (`AstEntropy`)."""
    import math

    def ent(s):
        if not s:
            return 0.0
        counts = {}
        for ch in s:
            counts[ch] = counts.get(ch, 0) + 1
        n = len(s)
        return -sum(c / n * math.log2(c / n) for c in counts.values())

    host = _host_strings(v)
    out = np.array([np.nan if s is None else ent(s) for s in host],
                   dtype=np.float32)
    return Vec.from_numpy(out)


def strdistance(v1: Vec, v2: Vec, measure: str = "lv",
                compare_empty: bool = True) -> Vec:
    """Pairwise string distance (`AstStrDistance`); Levenshtein ('lv') and
    Jaccard ('jaccard') measures."""

    def lev(a, b):
        if a == b:
            return 0
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[-1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

    def jac(a, b):
        sa, sb = set(a), set(b)
        return 1.0 - len(sa & sb) / max(len(sa | sb), 1)

    def jw(a, b):
        # Jaro-Winkler SIMILARITY with the standard p=0.1 prefix boost —
        # the reference's 'jw' measure (util.comparison.string.StringComparator)
        if a == b:
            return 1.0
        la, lb = len(a), len(b)
        if la == 0 or lb == 0:
            return 0.0
        window = max(la, lb) // 2 - 1
        ma = [False] * la
        mb = [False] * lb
        m = 0
        for i in range(la):
            lo, hi = max(0, i - window), min(lb, i + window + 1)
            for j in range(lo, hi):
                if not mb[j] and a[i] == b[j]:
                    ma[i] = mb[j] = True
                    m += 1
                    break
        if m == 0:
            return 0.0
        t = 0
        k = 0
        for i in range(la):
            if ma[i]:
                while not mb[k]:
                    k += 1
                if a[i] != b[k]:
                    t += 1
                k += 1
        jaro = (m / la + m / lb + (m - t / 2) / m) / 3.0
        prefix = 0
        for ca, cb in zip(a[:4], b[:4]):
            if ca != cb:
                break
            prefix += 1
        return jaro + prefix * 0.1 * (1.0 - jaro)

    def lcs_dist(a, b):
        # longest-common-subsequence edit distance (stringdist 'lcs')
        dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
        for i, ca in enumerate(a, 1):
            for j, cb in enumerate(b, 1):
                dp[i][j] = dp[i - 1][j - 1] + 1 if ca == cb else \
                    max(dp[i - 1][j], dp[i][j - 1])
        return len(a) + len(b) - 2 * dp[len(a)][len(b)]

    fns = {"jaccard": jac, "jw": jw, "lcs": lcs_dist, "lv": lev}
    if measure not in fns:
        raise ValueError(f"strDistance: unsupported measure '{measure}' "
                         f"(supported: {sorted(fns)})")
    fn = fns[measure]
    h1, h2 = _host_strings(v1), _host_strings(v2)
    out = np.full(len(h1), np.nan, dtype=np.float32)
    for i, (a, b) in enumerate(zip(h1, h2)):
        if a is None or b is None:
            continue
        if (a == "" or b == "") and not compare_empty:
            continue
        out[i] = fn(a, b)
    return Vec.from_numpy(out)


def tokenize(v: Vec, split: str = " ") -> Vec:
    """Flatten each string into one token per output row, NA row between
    originals (`AstTokenize` — the word2vec ingest shape)."""
    import re as _re

    host = _host_strings(v)
    out = []
    for s in host:
        if s is not None:
            out.extend(t for t in _re.split(split, s) if t)
        out.append(None)
    return Vec(None, len(out), type=T_STR,
               host_data=np.array(out, dtype=object))
