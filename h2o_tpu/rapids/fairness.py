"""Intersectional fairness metrics — `water/rapids/ast/prims/models/
AstFairnessMetrics.java` rebuilt host-side over one scoring pass.

The prim scores the frame once, buckets rows by the cross-product of the
protected columns' codes (+1 slot per column for NA), and produces:

- an ``overview`` frame: per non-empty group, the protected-column codes,
  the FairnessMetrics fields in the reference's declared order (tp, fp, tn,
  fn, total, relativeSize, accuracy, precision, f1, tpr, tnr, fpr, fnr, auc,
  aucpr, gini, selected, selectedRatio, logloss), the adverse-impact ratios
  ``AIR_<metric>`` against the reference group for everything except
  total/relativeSize, and ``p.value`` — Fisher's exact test on the 2x2
  selected-vs-reference table below the 10k-population threshold, the G-test
  above it (same switch and the R-compatible 1+1e-7 relative tolerance the
  Java uses).
- one ``thresholds_and_metrics_<group>`` frame per group: the binomial
  threshold/criteria table from the group's scores (the AUC2 ROC-info
  analog).

Everything is stdlib+numpy: the hypergeometric mass goes through lgamma, the
G-test p-value through erfc (chi-square sf at 1 dof).
"""

from __future__ import annotations

import math

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, Vec

#: FairnessMetrics field order (`AstFairnessMetrics.FairnessMetrics`)
_FIELDS = ["tp", "fp", "tn", "fn", "total", "relativeSize", "accuracy",
           "precision", "f1", "tpr", "tnr", "fpr", "fnr", "auc", "aucpr",
           "gini", "selected", "selectedRatio", "logloss"]
_SKIP_AIR = {"total", "relativeSize"}
_GTEST_THRESHOLD = 10_000
_FISHER_REL = 1 + 1e-7


def _auc_np(y: np.ndarray, p: np.ndarray) -> tuple[float, float]:
    """(auc, pr_auc) host-side: rank-statistic AUC with tie-averaged ranks,
    trapezoidal PR AUC over the threshold sweep."""
    npos = int(y.sum())
    nneg = len(y) - npos
    if npos == 0 or nneg == 0:
        return float("nan"), float("nan")
    order = np.argsort(p, kind="stable")
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    ps = p[order]
    # average ranks over ties
    uniq, start = np.unique(ps, return_index=True)
    for i, s in enumerate(start):
        e = start[i + 1] if i + 1 < len(start) else len(ps)
        if e - s > 1:
            ranks[order[s:e]] = (s + 1 + e) / 2.0
    auc = (ranks[y == 1].sum() - npos * (npos + 1) / 2.0) / (npos * nneg)
    # PR curve at descending unique thresholds
    desc = np.argsort(-p, kind="stable")
    yd = y[desc]
    tps = np.cumsum(yd)
    fps = np.cumsum(1 - yd)
    prec = tps / np.maximum(tps + fps, 1)
    rec = tps / npos
    pr_auc = float(np.trapezoid(prec, rec))
    return float(auc), pr_auc


def _fisher_exact(a: int, b: int, c: int, d: int) -> float:
    """Two-sided Fisher's exact test on [[a,b],[c,d]], summing all outcome
    probabilities ≤ p(observed)·(1+1e-7) like R / the reference."""
    n = a + b + c + d
    K = a + b     # selected margin
    N = a + c     # protected-group margin
    denom = math.lgamma(n + 1) - math.lgamma(N + 1) - math.lgamma(n - N + 1)

    def logp(i):
        if i < 0 or i > K or N - i > n - K:
            return -math.inf
        return (math.lgamma(K + 1) - math.lgamma(i + 1)
                - math.lgamma(K - i + 1)
                + math.lgamma(n - K + 1) - math.lgamma(N - i + 1)
                - math.lgamma(n - K - (N - i) + 1) - denom)

    p0 = math.exp(logp(a))
    pv = 0.0
    for i in range(max(a - d, 0), min(K, N) + 1):
        pi = math.exp(logp(i))
        if pi <= p0 * _FISHER_REL:
            pv += pi
    return min(pv, 1.0)


def _g_test(a: int, b: int, c: int, d: int) -> float:
    """G-test of independence on the 2x2 table; p from the chi-square
    survival at 1 dof (erfc(sqrt(G/2)))."""
    n = a + b + c + d
    rows = (a + b, c + d)
    cols = (a + c, b + d)
    exp_a = rows[0] * cols[0] / n
    exp_b = rows[0] * cols[1] / n
    exp_c = rows[1] * cols[0] / n
    exp_d = rows[1] * cols[1] / n
    g = 0.0
    for obs, exp in ((a, exp_a), (b, exp_b), (c, exp_c), (d, exp_d)):
        if obs > 0:
            g += obs * math.log(obs / exp)
    g *= 2.0
    return math.erfc(math.sqrt(max(g, 0.0) / 2.0))


def _p_value(ref: dict, grp: dict) -> float:
    a = int(grp["selected"])
    b = int(ref["selected"])
    c = int(grp["total"] - grp["selected"])
    d = int(ref["total"] - ref["selected"])
    try:
        if (ref["total"] < _GTEST_THRESHOLD
                and grp["total"] < _GTEST_THRESHOLD) \
                or a == 0 or b == 0 or c == 0 or d == 0:
            return _fisher_exact(a, b, c, d)
        return _g_test(a, b, c, d)
    except (ValueError, OverflowError):
        return float("nan")


def fairness_metrics(model, fr: Frame, protected_columns, reference,
                     favorable_class) -> dict:
    """Returns {name: Frame} with 'overview' + per-group threshold tables
    (`AstFairnessMetrics.apply`)."""
    from ..models.metrics import make_binomial_metrics

    if model.output.model_category != "Binomial":
        raise ValueError("Model has to be a binomial model!")
    pcols = list(protected_columns)
    for pc in pcols:
        if pc not in fr.names:
            raise ValueError(f"{pc} was not found in the frame!")
        if not fr.vec(pc).is_categorical():
            raise ValueError(f"{pc} has to be a categorical column!")
    resp = model.params.response_column
    dom = fr.vec(resp).domain or []
    if favorable_class not in dom:
        raise ValueError("Favourable class is not present in the response!")
    fav = dom.index(favorable_class)
    if reference is not None and len(reference) != len(pcols):
        raise ValueError(
            f"reference must name one level per protected column "
            f"({len(pcols)} expected, got {len(reference)})")
    if reference is not None:
        ref_idx = []
        for pc, rv in zip(pcols, reference):
            d = fr.vec(pc).domain
            if rv not in d:
                raise ValueError(
                    "Reference group is not present in the protected column")
            ref_idx.append(d.index(rv))
    else:
        ref_idx = None

    cards = [len(fr.vec(pc).domain) + 1 for pc in pcols]  # +1 = NA slot
    if float(np.prod(cards)) > 1e6:
        raise ValueError("Too many combinations of categories! Maximum "
                         "number of category combinations is 1e6.")

    # one scoring pass
    pred = model.predict(fr)
    plabel = pred.vec(0).to_numpy()
    p_fav = pred.vec(1 + fav).to_numpy()  # [label, p0, p1] layout
    y_raw = fr.vec(resp).to_numpy()
    ok = ~np.isnan(y_raw)
    y = np.where(ok, y_raw, 0).astype(np.int64)
    # favourable class becomes "1" (the reference flips labels when fav==0)
    yb = (y == fav).astype(np.int64)
    predb = (plabel.astype(np.int64) == fav).astype(np.int64)
    prob = np.clip(p_fav, 1e-15, 1 - 1e-15)

    # group keys: mixed-radix over protected codes, NA -> card-1 slot
    key = np.zeros(fr.nrow, dtype=np.int64)
    base = 1
    codes_per_col = []
    for pc, card in zip(pcols, cards):
        cc = fr.vec(pc).to_numpy()
        idx = np.where(np.isnan(cc), card - 1, cc).astype(np.int64)
        codes_per_col.append(idx)
        key += idx * base
        base *= card
    key = key[ok]
    yb, predb, prob = yb[ok], predb[ok], prob[ok]
    nrows = float(ok.sum())

    maxk = int(np.prod(cards))
    tp = np.bincount(key, weights=(yb & predb), minlength=maxk)
    tn = np.bincount(key, weights=((1 - yb) & (1 - predb)), minlength=maxk)
    fp = np.bincount(key, weights=((1 - yb) & predb), minlength=maxk)
    fn = np.bincount(key, weights=(yb & (1 - predb)), minlength=maxk)
    lls = np.bincount(key, weights=-(yb * np.log(prob)
                                     + (1 - yb) * np.log(1 - prob)),
                      minlength=maxk)

    def metrics_of(k) -> dict | None:
        t, n_, f, m_ = tp[k], tn[k], fp[k], fn[k]
        total = t + n_ + f + m_
        if total == 0:
            return None
        sel = key == k
        auc, aucpr = _auc_np(yb[sel], prob[sel])
        out = {
            "tp": t, "fp": f, "tn": n_, "fn": m_, "total": total,
            "relativeSize": total / nrows,
            "accuracy": (t + n_) / total,
            "precision": t / (f + t) if (f + t) else float("nan"),
            "f1": (2 * t) / (2 * t + f + m_) if (2 * t + f + m_)
            else float("nan"),
            "tpr": t / (t + m_) if (t + m_) else float("nan"),
            "tnr": n_ / (n_ + f) if (n_ + f) else float("nan"),
            "fpr": f / (f + n_) if (f + n_) else float("nan"),
            "fnr": m_ / (m_ + t) if (m_ + t) else float("nan"),
            "auc": auc, "aucpr": aucpr, "gini": 2 * auc - 1,
            "selected": t + f,
            "selectedRatio": (t + f) / total,
            "logloss": lls[k] / total,
        }
        return out

    groups = {k: m for k in range(maxk)
              if (m := metrics_of(k)) is not None}
    if ref_idx is not None:
        rk = 0
        b_ = 1
        for i, card in zip(ref_idx, cards):
            rk += i * b_
            b_ *= card
    else:
        rk = max(groups, key=lambda k: groups[k]["total"])
    ref = groups.get(rk)
    if ref is None:
        raise ValueError("reference group has no rows in the frame")

    def decode(k):
        out = []
        for card in cards:
            v = k % card
            k //= card
            out.append(float("nan") if v == card - 1 else float(v))
        return out

    # overview frame
    names = list(pcols) + list(_FIELDS) \
        + [f"AIR_{f}" for f in _FIELDS if f not in _SKIP_AIR] + ["p.value"]
    rows = []
    for k, m in groups.items():
        dec = decode(k)
        air = [m[f] / ref[f] if ref[f] else float("nan")
               for f in _FIELDS if f not in _SKIP_AIR]
        rows.append(dec + [m[f] for f in _FIELDS] + air + [_p_value(ref, m)])
    A = np.array(rows, dtype=np.float64)
    vecs = []
    for j, nm in enumerate(names):
        col = A[:, j].astype(np.float32)
        if j < len(pcols):
            vecs.append(Vec.from_numpy(col, type=T_CAT,
                                       domain=list(fr.vec(pcols[j]).domain)))
        else:
            vecs.append(Vec.from_numpy(col))
    result = {"overview": Frame(names, vecs)}

    # per-group threshold/criteria tables (the ROC-info frames); the
    # metrics object stores them as a dict of column arrays
    for k in groups:
        sel = key == k
        if not sel.any():
            continue
        import jax.numpy as jnp

        mm = make_binomial_metrics(jnp.asarray(yb[sel].astype(np.float32)),
                                   jnp.asarray(prob[sel]
                                               .astype(np.float32)))
        t = getattr(mm, "thresholds_and_metric_scores", None)
        if t is None:
            continue
        labels = []
        kk = k
        for pc, card in zip(pcols, cards):
            v = kk % card
            kk //= card
            labels.append("NaN" if v == card - 1
                          else str(fr.vec(pc).domain[v]))
        gname = "".join(ch if ch.isalnum() or ch == "," else "_"
                        for ch in ",".join(labels))
        result[f"thresholds_and_metrics_{gname}"] = Frame.from_dict(
            {cn: np.asarray(cv, dtype=np.float32)
             for cn, cv in t.items()})
    return result
