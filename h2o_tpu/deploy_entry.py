"""Container entry point — the `water.H2OApp.main` analog
(`h2o-app/src/main/java/water/H2OApp.java:7`).

Two supported modes, honest about JAX's multi-controller SPMD model:

- **Server mode (default, single host, any number of local chips)**: serve
  the REST API + status page over the local-device mesh. This is the
  `java -jar h2o.jar` experience.
- **SPMD driver mode (multi-host)**: JAX is multi-controller — EVERY process
  must issue the same computations, so a REST server on one pod cannot drive
  remote pods' chips. Multi-host jobs therefore run as SPMD driver scripts:
  the SAME Python program on every host, each calling
  ``h2o_tpu.parallel.cluster.init_cluster()`` first (the k8s manifest's
  headless service provides the coordinator address). Set
  ``H2O_TPU_DRIVER=your_module`` and this entry imports and runs it on every
  process after the cloud forms — the `hadoop jar h2odriver.jar` analog,
  where the driver is shipped to the cluster instead of the cluster being
  driven remotely."""

from __future__ import annotations

import importlib
import os
import sys
import time


def main() -> None:
    # the unified flag surface (`water/H2O.OptArgs` analog): CLI > env >
    # defaults, resolved values exported back to the environment so every
    # runtime consumer observes them; --help prints the full flag set
    from .utils import optargs

    args = optargs.parse(sys.argv[1:])
    optargs.ARGS = args
    assisted = args.assisted_clustering or os.environ.get(
        "H2O_ASSISTED_CLUSTERING", "").lower() in ("1", "true")
    if assisted:
        # the reference's H2O_ASSISTED_CLUSTERING flag: stand up the
        # port-8080 sidecar API and BLOCK until the operator's flatfile has
        # formed the cloud — jax.distributed.initialize must run before any
        # backend is touched, so nothing below may proceed first
        from .parallel.assisted import AssistedClusteringApi
        from .utils.log import info

        api = AssistedClusteringApi().start()
        info(f"assisted clustering API on :{api.port} — waiting for "
             "POST /clustering/flatfile")
        api.wait_until_clustered()
        info("assisted clustering: cloud formed")
    from .utils.knobs import raw

    driver = raw("H2O_TPU_DRIVER")
    if driver:
        from .parallel.cluster import init_cluster
        from .utils.log import info

        if not assisted:  # assisted mode already initialized the cloud
            init_cluster()
        import jax

        info(f"cloud up: process {jax.process_index()}/{jax.process_count()}, "
             f"{len(jax.devices())} global devices; running driver {driver}")
        mod = importlib.import_module(driver)
        mod.main()
        return

    # server mode: single host, local chips only
    from .api.server import H2OServer
    from .utils import compile_cache
    from .utils.log import info

    compile_cache.ensure()  # logs the cache dir itself when armed

    auth_check = None
    negotiate = None
    if args.ldap_login:
        # ldap[s]://host[:port]/dn-template (e.g. uid={},ou=people,dc=x)
        import urllib.parse as _up

        from .utils.ldap import LdapAuth

        u = _up.urlparse(args.ldap_login)
        auth_check = LdapAuth(
            u.hostname or args.ldap_login, port=u.port,
            dn_template=(u.path.lstrip("/") or "uid={}"),
            use_tls=u.scheme == "ldaps")
    elif args.pam_login:
        from .utils.pam import PamAuth

        auth_check = PamAuth()
    if args.kerberos_login:
        from .utils.krb import SpnegoAuth

        negotiate = SpnegoAuth()
    server = H2OServer(
        port=args.port, name=args.name,
        hash_login=args.hash_login or None,
        ssl_certfile=args.ssl_certfile or None,
        ssl_keyfile=args.ssl_keyfile or None,
        auth_check=auth_check, negotiate_auth=negotiate).start()
    info(f"REST serving on {server.url}")
    while True:
        time.sleep(60)


if __name__ == "__main__":
    main()
