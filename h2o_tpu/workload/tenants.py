"""Tenant registry + request-scoped tenant/priority context.

The reference platform's "millions of users" story assumes isolated
tenants on shared pools; its mechanism is priority fork-join queues
(`H2O.submitTask`), with no per-tenant accounting at all. Here a tenant
is a named principal with a fair-share **weight** and an optional HBM
**quota fraction**; everything it submits — training jobs, grid
searches, ingest — is stamped with its name and debits the ONE
reservation ledger in `backend/memory.py` (PR 8's `reserve_bytes`
generalized past serving; no scheduler-only shadow accounting).

Identity flows by context, not plumbing: `H2O_TPU_TENANT` names the
tenant a process submits as, the REST client forwards it as the
``X-H2O-TPU-Tenant`` header, and the server scopes each request with
:func:`request_scope` so every Job created underneath lands on the
right tenant. Legacy callers that never mention tenants run as
``default`` — unlimited quota, weight 1, exactly the old behavior.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field

from ..utils import knobs

DEFAULT = "default"


@dataclass
class Tenant:
    name: str
    #: fair-share tickets multiplier in the dispatch lottery and the
    #: MRTask gate's virtual-time denominator
    weight: float = 1.0
    #: fraction of memory.base_hbm_limit_bytes() this tenant may hold in
    #: reservations; None = the H2O_TPU_WORKLOAD_QUOTA knob (or unlimited)
    quota_fraction: float | None = None
    # lifetime counters (written under the workload manager's lock; read
    # by /3/Workload and the Prometheus provider)
    preemptions: int = 0
    sheds: int = 0
    rejected: int = 0

    def asdict(self) -> dict:
        return {"name": self.name, "weight": self.weight,
                "quota_fraction": self.quota_fraction,
                "preemptions": self.preemptions, "sheds": self.sheds,
                "rejected": self.rejected}


_REGISTRY: dict[str, Tenant] = {}
_LOCK = threading.Lock()

#: request/job-scoped identity — set by the server around each routed
#: request and by the manager around each dispatched job, so nested
#: builds (CV folds, grid candidates) inherit without plumbing
_CURRENT: ContextVar[str] = ContextVar("h2o_tpu_tenant", default="")
_PRIORITY: ContextVar[str] = ContextVar("h2o_tpu_priority", default="")


def get(name: str) -> Tenant:
    """The tenant record, created on first reference (a tenant is a name,
    not a provisioning step — quota/weight attach via configure())."""
    t = _REGISTRY.get(name)
    if t is None:
        with _LOCK:
            t = _REGISTRY.setdefault(name, Tenant(name=name))
    return t


def configure(name: str, weight: float | None = None,
              quota_fraction: float | None = None) -> Tenant:
    """Set a tenant's fair-share weight and/or quota fraction (the
    `POST /3/Workload` body). Explicit configuration wins over the
    H2O_TPU_WORKLOAD_QUOTA knob."""
    t = get(name)
    with _LOCK:
        if weight is not None:
            if weight <= 0:
                raise ValueError(f"tenant weight must be > 0, got {weight}")
            t.weight = float(weight)
        if quota_fraction is not None:
            if not (0.0 < quota_fraction <= 1.0):
                raise ValueError(
                    f"quota_fraction must be in (0, 1], got {quota_fraction}")
            t.quota_fraction = float(quota_fraction)
    return t


def all_tenants() -> list[Tenant]:
    with _LOCK:
        return list(_REGISTRY.values())


def weight(name: str) -> float:
    return get(name).weight


def _knob_quota_map() -> dict[str, float]:
    """H2O_TPU_WORKLOAD_QUOTA = 'tenant=frac,...' parsed per read so
    operators/tests can retune a live process; malformed entries raise
    loudly (a silently dropped quota is an isolation hole)."""
    raw = knobs.get_str("H2O_TPU_WORKLOAD_QUOTA")
    out: dict[str, float] = {}
    for tok in filter(None, (t.strip() for t in raw.split(","))):
        name, sep, val = tok.partition("=")
        if not sep or not name:
            raise ValueError(
                f"bad H2O_TPU_WORKLOAD_QUOTA entry {tok!r} — grammar: "
                f"'<tenant>=<fraction>,...'")
        out[name] = float(val)
    return out


def quota_fraction(name: str) -> float | None:
    t = get(name)
    if t.quota_fraction is not None:
        return t.quota_fraction
    return _knob_quota_map().get(name)


def quota_bytes(name: str) -> int | None:
    """The tenant's reservation budget in bytes, or None for unlimited.
    Fractions are taken of the PRE-reservation HBM budget (the same
    base the serving quota uses); with no resolvable budget (CPU dev
    without H2O_TPU_HBM_LIMIT_BYTES) admission stays open — quotas are
    a deployment posture, not a dev-box tax."""
    frac = quota_fraction(name)
    if frac is None:
        return None
    from ..backend import memory

    base = memory.base_hbm_limit_bytes()
    if not base:
        return None
    return int(frac * base)


# -- request/job context ------------------------------------------------------
def current() -> str:
    """The tenant the calling context submits as: request/job scope if
    set, else the H2O_TPU_TENANT knob, else 'default'."""
    return _CURRENT.get() or knobs.get_str("H2O_TPU_TENANT") or DEFAULT


def current_priority() -> str | None:
    """Priority class requested by the surrounding scope (X-H2O-TPU-
    Priority header / managed dispatch), or None when unset."""
    return _PRIORITY.get() or None


@contextmanager
def request_scope(tenant: str | None = None, priority: str | None = None):
    """Scope tenant/priority identity around a request or a dispatched
    job body; None leaves the enclosing value in place."""
    toks = []
    if tenant:
        toks.append((_CURRENT, _CURRENT.set(tenant)))
    if priority:
        toks.append((_PRIORITY, _PRIORITY.set(priority)))
    try:
        yield
    finally:
        for var, tok in reversed(toks):
            var.reset(tok)


def _reset_for_tests() -> None:
    with _LOCK:
        _REGISTRY.clear()
