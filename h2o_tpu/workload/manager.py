"""Workload manager — admission, fair-share dispatch, chunk-boundary
preemption over the one process group.

The scheduler tier the reference keeps in `H2O.submitTask`'s priority
fork-join queues, rebuilt for the TPU platform's actual contention
points: HBM (tenant quotas debit the PR 8 reservation ledger — ONE
accounting), the training slot (jobs queue and drain under weighted
fair-share, deterministic under H2O_TPU_WORKLOAD_SEED), and the SLO
plane (PR 15's `slo.worst_burn` + `/3/Health` typed reasons decide
WHICH tenant sheds under pressure).

Lifecycle of a managed job::

    submit ──quota──▶ QUEUED ──lottery──▶ RUNNING ──▶ FINISHED
                 │                  ▲        │
                 ▼ over-quota       │        ▼ preempt @ chunk boundary
       WorkloadAdmissionError       └──── PARKED  (state checkpointed
       (REST: 429 + Retry-After)           host-side, HBM reservation
                                           released, re-admitted when
                                           pressure drops — resumed
                                           forest bit-equal, PR 5)

Preemption is cooperative and boundary-aligned: `request_preempt()`
flags the job, the training loop's `_recovery_tick` observes it at the
next chunk/epoch boundary, force-checkpoints through `TrainingRecovery`
and unwinds with ``JobPreempted``. A job that never armed recovery is
not preemptible — the manager never discards work.

With ``H2O_TPU_WORKLOAD_SLOTS=0`` (the default) every submit dispatches
immediately: legacy single-tenant behavior, no queueing, no threads.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextvars import ContextVar

from ..backend import memory
from ..backend.jobs import Job, JobPreempted
from ..utils import knobs, sanitizer, slo, telemetry
from . import fairshare, tenants

#: lower ordinal = stronger lane (Job.PRIORITIES order)
_PRIO_ORD = {p: i for i, p in enumerate(Job.PRIORITIES)}

#: finished-entry history kept for /3/Workload
_HISTORY = 64


class WorkloadAdmissionError(Exception):
    """Typed over-quota rejection — api/server.py maps it to HTTP 429
    with a Retry-After header, mirroring serving's AdmissionError."""

    def __init__(self, tenant: str, cost_bytes: int, quota_bytes: int,
                 used_bytes: int, retry_after_s: float):
        self.tenant = tenant
        self.cost_bytes = int(cost_bytes)
        self.quota_bytes = int(quota_bytes)
        self.used_bytes = int(used_bytes)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"tenant {tenant!r} over quota: submit needs {cost_bytes} B "
            f"with {used_bytes} B already reserved of a {quota_bytes} B "
            f"quota — retry after {retry_after_s:.0f}s")


class _Entry:
    """One managed submission, queue→slot→park lifecycle included."""

    __slots__ = ("id", "job", "fn", "tenant", "priority", "cost_bytes",
                 "state", "losses", "submit_ts", "queued_ts", "start_ts",
                 "end_ts", "recovery_dir", "preempt_count", "reserved",
                 "event", "resume", "resume_pending", "shed", "ready_ts")

    def __init__(self, eid: int, job: Job, fn, tenant: str, priority: str,
                 cost_bytes: int):
        self.id = eid
        self.job = job
        self.fn = fn
        self.tenant = tenant
        self.priority = priority
        self.cost_bytes = int(cost_bytes)
        self.state = "QUEUED"
        self.losses = 0                 # consecutive lottery losses (aging)
        self.submit_ts = time.time()
        self.queued_ts: float | None = None
        self.start_ts: float | None = None
        self.end_ts: float | None = None
        self.recovery_dir: str | None = None
        self.preempt_count = 0
        self.reserved = False           # holds a ledger reservation now
        self.event: threading.Event | None = None  # foreground handshake
        self.resume = False             # dispatch = resume_training replay
        self.resume_pending = False     # next nested job attach wins
        self.shed = False               # parked by the shed policy
        self.ready_ts: float | None = None  # parked: earliest re-admission

    def describe(self) -> dict:
        job = self.job
        state = self.state
        if state == "FINISHED" and job is not None:
            state = job.status
        out = {"id": f"wl-{self.id}", "job": str(job.key) if job else None,
               "tenant": self.tenant, "priority": self.priority,
               "state": state, "preemptions": self.preempt_count,
               "cost_bytes": self.cost_bytes}
        if self.recovery_dir:
            out["recovery_dir"] = self.recovery_dir
        return out


#: the entry whose slot the calling context runs under — nested builds
#: (CV folds, grid candidates, resume replays) dispatch inline in the
#: parent's slot instead of queueing (which would deadlock a bounded
#: slot count against its own children)
_SCOPE: ContextVar["_Entry | None"] = ContextVar("h2o_tpu_workload_scope",
                                                 default=None)


class WorkloadManager:
    def __init__(self):
        self._lock = sanitizer.make_lock("Workload._state")
        self._ids = itertools.count(1)
        self._queue: list[_Entry] = []
        self._running: dict[int, _Entry] = {}
        self._parked: list[_Entry] = []
        self._done: deque = deque(maxlen=_HISTORY)
        self._ordinal = 0               # lottery drawing counter
        self._wait_windows: dict[str, deque] = {}
        self._thread: threading.Thread | None = None
        self._resume_threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- knobs ---------------------------------------------------------------
    @staticmethod
    def _slots() -> int:
        return knobs.get_int("H2O_TPU_WORKLOAD_SLOTS")

    @staticmethod
    def _retry_s() -> float:
        return float(max(knobs.get_int("H2O_TPU_WORKLOAD_RETRY_S"), 1))

    # -- submission ----------------------------------------------------------
    def submit(self, job: Job, fn, *, background: bool = True,
               cost_bytes: int = 0, tenant: str | None = None,
               priority: str | None = None) -> Job:
        """Admit + dispatch one job. Stamps tenant/priority on the Job,
        debits the tenant quota through the reservation ledger, then
        either dispatches (free slot / unmanaged), queues for the
        fair-share lottery, or raises WorkloadAdmissionError."""
        parent = _SCOPE.get()
        if parent is not None:
            # nested build inside a managed slot (CV fold, grid
            # candidate, resume replay): the parent's admission and
            # reservation already cover it — inherit identity, attach
            # to a pending resume, run in place
            job.tenant, job.priority = parent.tenant, parent.priority
            if parent.resume_pending:
                parent.job = job
                parent.resume_pending = False
            job.start(fn, background=background)
            return job

        name = tenant or tenants.current()
        prio = priority or tenants.current_priority() or "batch"
        if prio not in _PRIO_ORD:
            raise ValueError(
                f"unknown priority {prio!r} — one of {Job.PRIORITIES}")
        job.tenant, job.priority = name, prio
        entry = _Entry(next(self._ids), job, fn, name, prio, cost_bytes)

        victim = None
        with self._lock:
            self._admit_locked(entry)       # raises over-quota
            telemetry.inc("workload.submitted.count")
            slots = self._slots()
            if slots <= 0 or len(self._running) < slots:
                self._grant_locked(entry)
            else:
                entry.queued_ts = time.time()
                if not background:
                    entry.event = threading.Event()
                self._queue.append(entry)
                victim = self._preempt_victim_locked(entry)
            self._sync_gauges_locked()
        if victim is not None:
            victim.job.request_preempt()
        if self._slots() > 0:
            self._ensure_thread()

        if entry.state == "RUNNING":
            job.start(self._wrap(entry, fn), background=background)
            return job
        if entry.event is not None:
            # foreground submission that had to queue: block the caller
            # until the lottery grants the slot, then run in place
            entry.event.wait()
            job.start(self._wrap(entry, fn), background=False)
            return job
        return job

    def _admit_locked(self, entry: _Entry) -> None:
        quota = tenants.quota_bytes(entry.tenant)
        if quota is None:
            return                      # unlimited tenant / no HBM budget
        used = sum(e.cost_bytes for e in self._live_entries()
                   if e.tenant == entry.tenant and e.reserved)
        if used + entry.cost_bytes > quota:
            tenants.get(entry.tenant).rejected += 1
            telemetry.inc("workload.rejected.count")
            raise WorkloadAdmissionError(
                entry.tenant, entry.cost_bytes, quota, used,
                retry_after_s=self._retry_s())
        self._reserve(entry)

    def _reserve(self, entry: _Entry) -> None:
        if entry.cost_bytes > 0 and tenants.quota_bytes(entry.tenant) is not None:
            memory.reserve_bytes(self._owner(entry), entry.cost_bytes)
            entry.reserved = True

    def _release(self, entry: _Entry) -> None:
        if entry.reserved:
            memory.release_bytes(self._owner(entry))
            entry.reserved = False

    @staticmethod
    def _owner(entry: _Entry) -> str:
        return f"workload:{entry.tenant}:{entry.id}"

    def _live_entries(self):
        return list(self._queue) + list(self._running.values()) \
            + list(self._parked)

    # -- dispatch ------------------------------------------------------------
    def _grant_locked(self, entry: _Entry) -> None:
        now = time.time()
        entry.state = "RUNNING"
        entry.start_ts = now
        entry.losses = 0
        self._running[entry.id] = entry
        telemetry.inc("workload.dispatch.count")
        if entry.queued_ts is not None:
            wait = max(now - entry.queued_ts, 0.0)
            telemetry.observe("workload.queue.wait.seconds", wait)
            slo.note("workload.wait", wait)
            win = self._wait_windows.setdefault(entry.tenant,
                                                deque(maxlen=512))
            win.append((now, wait))
            entry.queued_ts = None

    def _pick_locked(self) -> _Entry:
        """The fair-share lottery: strongest priority lane present wins
        the drawing; within the lane, tickets are tenant weights and the
        draw is splitmix64(seed, ordinal) — deterministic replay under a
        seed. Entries past the aging bound are force-dispatched FIFO
        regardless of lane (the starvation bound)."""
        q = self._queue
        aging = max(knobs.get_int("H2O_TPU_WORKLOAD_AGING"), 1)
        aged = [e for e in q if e.losses >= aging]
        if aged:
            chosen = aged[0]
        else:
            best = min(_PRIO_ORD[e.priority] for e in q)
            lane = [e for e in q if _PRIO_ORD[e.priority] == best]
            total = sum(tenants.weight(e.tenant) for e in lane)
            r = fairshare.draw(knobs.get_int("H2O_TPU_WORKLOAD_SEED"),
                               self._ordinal) * total
            self._ordinal += 1
            acc, chosen = 0.0, lane[-1]
            for e in lane:
                acc += tenants.weight(e.tenant)
                if r < acc:
                    chosen = e
                    break
        for e in q:
            if e is not chosen:
                e.losses += 1
        q.remove(chosen)
        return chosen

    def _preempt_victim_locked(self, arrival: _Entry) -> "_Entry | None":
        """A stronger-lane arrival with no free slot preempts the
        weakest running PREEMPTIBLE entry (latest start on ties — least
        sunk work lost). Returns the victim; the caller requests the
        preempt outside the manager lock."""
        cand = [e for e in self._running.values()
                if e.job is not None and e.job.preemptible
                and _PRIO_ORD[e.priority] > _PRIO_ORD[arrival.priority]]
        if not cand:
            return None
        return max(cand, key=lambda e: (_PRIO_ORD[e.priority],
                                        e.start_ts or 0.0))

    def _pump(self) -> None:
        """Re-admit due parked entries, then fill free slots from the
        queue. Launches happen outside the lock."""
        to_launch: list[_Entry] = []
        victim = None
        with self._lock:
            slots = self._slots()
            now = time.time()
            if slots > 0:
                for e in list(self._parked):
                    if e.ready_ts is not None and now >= e.ready_ts:
                        self._parked.remove(e)
                        e.state = "QUEUED"
                        e.queued_ts = now
                        e.losses = 0
                        e.resume = True
                        self._queue.append(e)
                while self._queue and len(self._running) < slots:
                    e = self._pick_locked()
                    try:
                        self._admit_locked(e)
                    except WorkloadAdmissionError:
                        # quota re-filled by a later finish/park — park
                        # the entry rather than dropping it
                        e.state = "PARKED"
                        e.ready_ts = now + self._retry_s()
                        self._parked.append(e)
                        continue
                    self._grant_locked(e)
                    to_launch.append(e)
                if self._queue and len(self._running) >= slots:
                    strongest = min(
                        self._queue, key=lambda e: _PRIO_ORD[e.priority])
                    victim = self._preempt_victim_locked(strongest)
            self._sync_gauges_locked()
        if victim is not None:
            victim.job.request_preempt()
        for e in to_launch:
            self._launch(e)

    def _launch(self, entry: _Entry) -> None:
        if entry.resume:
            self._spawn_resume(entry)
        elif entry.event is not None:
            entry.event.set()           # foreground caller runs it
        else:
            entry.job.start(self._wrap(entry, entry.fn), background=True)

    # -- the managed run wrapper --------------------------------------------
    def _wrap(self, entry: _Entry, fn):
        def run():
            with tenants.request_scope(entry.tenant, entry.priority):
                stok = _SCOPE.set(entry)
                try:
                    result = fn()
                except JobPreempted as e:
                    self._park(entry, e.recovery_dir)
                    raise
                except BaseException:
                    self._finish(entry)
                    raise
                finally:
                    _SCOPE.reset(stok)
            inner = entry.job
            if inner is not None and inner.status == Job.PREEMPTED:
                # a nested resume replay was preempted again: its _run
                # absorbed the JobPreempted, so re-raise to park and to
                # mark the outer job PREEMPTED too
                self._park(entry, inner.preempt_dir)
                raise JobPreempted(str(inner.key), inner.preempt_dir)
            self._finish(entry)
            return result

        return run

    def _finish(self, entry: _Entry) -> None:
        with self._lock:
            self._release(entry)
            self._running.pop(entry.id, None)
            entry.state = "FINISHED"
            entry.end_ts = time.time()
            self._done.append(entry)
            self._sync_gauges_locked()
        self._pump()

    def _park(self, entry: _Entry, recovery_dir: str | None) -> None:
        with self._lock:
            self._release(entry)        # HBM back through the one ledger
            self._running.pop(entry.id, None)
            entry.state = "PARKED"
            entry.recovery_dir = recovery_dir or entry.recovery_dir
            entry.preempt_count += 1
            tenants.get(entry.tenant).preemptions += 1
            if entry.shed:
                entry.shed = False
                entry.ready_ts = time.time() + self._retry_s()
                tenants.get(entry.tenant).sheds += 1
                telemetry.inc("workload.shed.count")
            else:
                entry.ready_ts = time.time()
            if entry.recovery_dir is None:
                # preempted without a checkpoint to replay (shouldn't
                # happen — the boundary hook refuses preemption when no
                # recovery is armed) — nothing to resume, record as done
                entry.state = "FINISHED"
                self._done.append(entry)
            else:
                self._parked.append(entry)
            self._sync_gauges_locked()
        self._pump()

    def _spawn_resume(self, entry: _Entry) -> None:
        telemetry.inc("workload.resume.count")
        entry.resume = False
        entry.resume_pending = True
        wrapped = self._wrap(entry, self._resume_fn(entry))

        def guard():
            try:
                wrapped()
            except BaseException:  # noqa: BLE001 — outcome lives on the entry/job
                pass

        # drained through _resume_threads in stop(); the analyzer cannot
        # see joins through list membership
        t = threading.Thread(  # graftlint: disable=unjoined-thread
            target=telemetry.carry_context(guard),
            daemon=True, name=f"workload-resume-{entry.id}")
        with self._lock:
            self._resume_threads = [r for r in self._resume_threads
                                    if r.is_alive()]
            self._resume_threads.append(t)
        t.start()

    @staticmethod
    def _resume_fn(entry: _Entry):
        def run():
            from ..models.model_base import resume_training

            return resume_training(entry.recovery_dir)

        return run

    # -- shed policy (the PR 15 signal plane feeding the scheduler) ----------
    def shed_check(self, snap: dict | None = None) -> list[str]:
        """One shed-policy evaluation. Reads the /3/Health payload
        (injectable for tests): typed memory/serving pressure — or an
        SLO burn past H2O_TPU_WORKLOAD_SHED_BURN — preempts the highest-
        pressure tenant's weakest running job (parked with a retry
        delay); watchdog hung-job/trip reasons requeue the implicated
        managed job instead of paging. Returns the typed decisions."""
        if snap is None:
            from ..utils import health

            snap = health.snapshot()
        reasons = {d.get("reason") for d in snap.get("degraded", ())}
        decisions: list[str] = []
        burn_max = knobs.get_int("H2O_TPU_WORKLOAD_SHED_BURN")
        worst = 0.0
        for rec in (snap.get("slo") or {}).values():
            worst = max(worst, rec.get("burn") or 0.0)
        pressure = bool(reasons & {"cleaner-headroom",
                                   "serving-queue-saturation"})
        if burn_max > 0 and worst > burn_max:
            pressure = True
        victims: list[_Entry] = []
        if pressure:
            with self._lock:
                v = self._shed_victim_locked()
                if v is not None:
                    v.shed = True
                    victims.append(v)
                    decisions.append(f"shed:{v.tenant}:wl-{v.id}")
        if reasons & {"job-heartbeat", "watchdog-trip"}:
            stale = set()
            for d in snap.get("degraded", ()):
                for j in d.get("jobs", ()) or ():
                    key = j.get("subject") or j.get("job")
                    if key:
                        stale.add(str(key))
            with self._lock:
                for e in self._running.values():
                    if (e.job is not None and e.job.preemptible
                            and str(e.job.key) in stale):
                        victims.append(e)
                        decisions.append(f"requeue:{e.tenant}:wl-{e.id}")
                        telemetry.inc("workload.requeue.count")
        for v in victims:
            v.job.request_preempt()
        return decisions

    def _shed_victim_locked(self) -> "_Entry | None":
        """WHICH tenant sheds: the one holding the most pressure per
        unit of fair-share weight (reservation bytes + a slot each per
        running job); within it, the weakest-priority, latest-started
        running preemptible entry."""
        cand = [e for e in self._running.values()
                if e.job is not None and e.job.preemptible]
        if not cand:
            return None
        by_tenant: dict[str, list[_Entry]] = {}
        for e in cand:
            by_tenant.setdefault(e.tenant, []).append(e)

        def pressure(name: str) -> float:
            es = by_tenant[name]
            held = sum(e.cost_bytes for e in es if e.reserved)
            return (held + len(es)) / tenants.weight(name)

        worst = max(by_tenant, key=pressure)
        return max(by_tenant[worst],
                   key=lambda e: (_PRIO_ORD[e.priority], e.start_ts or 0.0))

    def preempt_weakest(self) -> bool:
        """Serving placement pressure hook (serving/control.py): yield
        HBM by preempting the weakest running preemptible entry. Returns
        whether a preempt was requested."""
        with self._lock:
            cand = [e for e in self._running.values()
                    if e.job is not None and e.job.preemptible]
            victim = max(cand, key=lambda e: (_PRIO_ORD[e.priority],
                                              e.start_ts or 0.0)) \
                if cand else None
            if victim is not None:
                victim.shed = True
        if victim is None:
            return False
        victim.job.request_preempt()
        return True

    # -- maintenance thread --------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        tick_ms = knobs.get_int("H2O_TPU_WORKLOAD_TICK_MS")
        if tick_ms <= 0:
            return
        self._stop.clear()
        # self-rooted supervisor: spans it emits must not nest under
        # whichever request happened to start it
        self._thread = threading.Thread(  # graftlint: disable=thread-without-trace-context
            target=self._loop, daemon=True, name="workload-manager")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(
                max(knobs.get_int("H2O_TPU_WORKLOAD_TICK_MS"), 100) / 1000.0):
            try:
                self._pump()
            except Exception:  # noqa: BLE001 — the pump must outlive one bad entry
                pass
            try:
                with self._lock:
                    active = bool(self._running or self._parked
                                  or self._queue)
                if active:
                    self.shed_check()
            except Exception:  # noqa: BLE001
                pass

    # -- introspection -------------------------------------------------------
    def _sync_gauges_locked(self) -> None:
        telemetry.set_gauge("workload.running", len(self._running))
        telemetry.set_gauge("workload.queue.depth", len(self._queue))
        telemetry.set_gauge("workload.parked", len(self._parked))

    def tenant_burn(self, name: str) -> float | None:
        """Per-tenant queue-wait burn against the workload.wait SLO
        (same construction as slo.py's latency burn, scoped to the
        tenant's own dispatch window)."""
        win = self._wait_windows.get(name)
        if not win:
            return None
        obj = slo.objective("workload.wait")
        thr = obj.p99_ms / 1000.0
        horizon = time.time() - slo.window_s()
        recent = [w for (ts, w) in win if ts >= horizon]
        if not recent:
            return None
        breach = sum(1 for w in recent if w > thr) / len(recent)
        return round(breach / 0.01, 4)

    def snapshot(self) -> dict:
        """The `GET /3/Workload` payload: scheduler config, per-tenant
        accounting (quota, reservations, lanes, burn), and every live +
        recently finished entry."""
        with self._lock:
            live = self._live_entries()
            entries = [e.describe() for e in live] \
                + [e.describe() for e in self._done]
            running = dict(self._running)
            queue = list(self._queue)
            parked = list(self._parked)
        names = {t.name for t in tenants.all_tenants()} \
            | {e.tenant for e in live}
        per_tenant = {}
        for name in sorted(names):
            t = tenants.get(name)
            per_tenant[name] = {
                **t.asdict(),
                "quota_bytes": tenants.quota_bytes(name),
                "reserved_bytes": sum(
                    e.cost_bytes for e in live
                    if e.tenant == name and e.reserved),
                "running": sum(1 for e in running.values()
                               if e.tenant == name),
                "queued": sum(1 for e in queue if e.tenant == name),
                "parked": sum(1 for e in parked if e.tenant == name),
                "burn": self.tenant_burn(name),
            }
        return {
            "managed": self._slots() > 0,
            "slots": self._slots(),
            "seed": knobs.get_int("H2O_TPU_WORKLOAD_SEED"),
            "aging": knobs.get_int("H2O_TPU_WORKLOAD_AGING"),
            "priorities": list(Job.PRIORITIES),
            "tenants": per_tenant,
            "entries": entries,
            "counters": {
                name: telemetry.value(f"workload.{name}.count")
                for name in ("submitted", "rejected", "dispatch",
                             "preempt", "resume", "shed", "requeue")},
        }

    def _prom_lines(self) -> list[str]:
        """Per-tenant Prometheus series (h2o_tpu_tenant_*{tenant=...}) —
        the PR 8 provider pattern, labels escaped."""
        esc = telemetry.prom_label_escape
        with self._lock:
            live = self._live_entries()
            running = list(self._running.values())
            queue = list(self._queue)
        names = sorted({t.name for t in tenants.all_tenants()}
                       | {e.tenant for e in live})
        if not names:
            return []
        gauges = [
            ("h2o_tpu_tenant_running_jobs", "gauge",
             "managed jobs of this tenant holding a slot",
             lambda n: sum(1 for e in running if e.tenant == n)),
            ("h2o_tpu_tenant_queued_jobs", "gauge",
             "managed jobs of this tenant waiting for a slot",
             lambda n: sum(1 for e in queue if e.tenant == n)),
            ("h2o_tpu_tenant_reserved_bytes", "gauge",
             "HBM this tenant holds in the reservation ledger",
             lambda n: sum(e.cost_bytes for e in live
                           if e.tenant == n and e.reserved)),
            ("h2o_tpu_tenant_preemptions_total", "counter",
             "boundary preemptions of this tenant's jobs",
             lambda n: tenants.get(n).preemptions),
            ("h2o_tpu_tenant_shed_total", "counter",
             "shed-policy preemptions charged to this tenant",
             lambda n: tenants.get(n).sheds),
        ]
        lines = []
        for metric, kind, doc, fn in gauges:
            lines.append(f"# HELP {metric} {doc}")
            lines.append(f"# TYPE {metric} {kind}")
            for n in names:
                lines.append(f'{metric}{{tenant="{esc(n)}"}} {fn(n)}')
        return lines

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=2.0)
        self._thread = None
        with self._lock:
            pending = list(self._resume_threads)
            self._resume_threads = []
        for t in pending:
            if t.is_alive():
                t.join(timeout=2.0)


# ---------------------------------------------------------------------------
# module surface
# ---------------------------------------------------------------------------
_MANAGER: WorkloadManager | None = None
_MANAGER_LOCK = threading.Lock()


def manager() -> WorkloadManager:
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = WorkloadManager()
    return _MANAGER


def submit(job: Job, fn, **kw) -> Job:
    return manager().submit(job, fn, **kw)


def snapshot() -> dict:
    return manager().snapshot()


def note_serving_pressure() -> bool:
    """serving/control.py calls this when placement admission fails:
    training yields HBM at its next boundary so the placement's retry
    (the client honors Retry-After) finds room. No-op without a live
    manager — existing serving paths pay nothing."""
    m = _MANAGER
    if m is None:
        return False
    return m.preempt_weakest()


def frame_cost(obj) -> int:
    """Submission cost estimate when the caller has no better number:
    the training frame's full-precision footprint (nrow × ncol × 4).
    Accepts a params object (reads ``training_frame``) or a Frame."""
    fr = getattr(obj, "training_frame", obj)
    if fr is None:
        return 0
    try:
        return int(fr.nrow) * max(len(fr.names), 1) * 4
    except Exception:  # noqa: BLE001 — an estimate, never a failure source
        return 0


def _prometheus_tenant_lines() -> list[str]:
    m = _MANAGER
    if m is None:
        return []
    return m._prom_lines()


telemetry.add_prometheus_provider(_prometheus_tenant_lines)


def _reset_for_tests() -> None:
    """Stop the maintenance thread, release every managed reservation
    and drop all scheduler + tenant state (test isolation)."""
    global _MANAGER
    m = _MANAGER
    if m is not None:
        m.stop()
        with m._lock:
            for e in m._live_entries():
                if e.reserved:
                    memory.release_bytes(m._owner(e))
                    e.reserved = False
    with _MANAGER_LOCK:
        _MANAGER = None
    tenants._reset_for_tests()
    fairshare._reset_for_tests()
