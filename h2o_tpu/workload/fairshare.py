"""Weighted fair-share primitives: the dispatch lottery hash and the
MRTask dispatch gate.

Two deterministic mechanisms, no RNG state:

- :func:`draw` — the PR 8 router's splitmix64 construction mapping
  ``(seed, drawing ordinal)`` to a unit float. The job-queue lottery
  uses it so the same seed + the same submission sequence replays the
  same dispatch order (the property the router's traffic splits pin).
- :class:`FairGate` — a weighted-fair semaphore for MRTask driver
  dispatch: waiters wake lowest-virtual-time-first (``dispatches so
  far / tenant weight``, FIFO on ties), so a tenant hammering the mesh
  cannot monopolize the dispatch choke point while another starves.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_MASK = 0xFFFFFFFFFFFFFFFF


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return z ^ (z >> 31)


def draw(seed: int, ordinal: int) -> float:
    """Unit float in [0, 1) for the ``ordinal``-th lottery drawing under
    ``seed`` — same finalizer chain as serving/router.py's spray hash."""
    h = _splitmix64((seed & _MASK) ^ _splitmix64(ordinal & _MASK))
    return (h >> 11) / float(1 << 53)


class FairGate:
    """Bounded concurrent dispatch with weighted-fair wakeup order.

    ``acquire`` blocks while ``slots`` are busy; among the blocked, the
    waiter whose tenant has the lowest virtual time (grants so far
    divided by weight) goes first, with FIFO breaking ties. Purely
    host-side — it gates the MRTask driver's program launch, never the
    device work itself.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._active = 0
        self._grants: dict[str, int] = {}
        self._waiters: list[tuple[float, int]] = []
        self._seq = 0

    def _vtime(self, tenant: str, weight: float) -> float:
        return self._grants.get(tenant, 0) / max(weight, 1e-9)

    def acquire(self, tenant: str, slots: int, weight: float) -> None:
        with self._cond:
            if self._active < slots and not self._waiters:
                self._grant(tenant)
                return
            me = (self._vtime(tenant, weight), self._seq)
            self._seq += 1
            self._waiters.append(me)
            try:
                while not (self._active < slots
                           and min(self._waiters) == me):
                    # bounded wait: a missed notify degrades to a 100ms
                    # re-check, never a hang
                    self._cond.wait(timeout=0.1)
            finally:
                self._waiters.remove(me)
            self._grant(tenant)
            self._cond.notify_all()     # min(waiters) changed

    def _grant(self, tenant: str) -> None:
        self._active += 1
        self._grants[tenant] = self._grants.get(tenant, 0) + 1

    def release(self) -> None:
        with self._cond:
            self._active -= 1
            self._cond.notify_all()

    def grants(self) -> dict[str, int]:
        with self._cond:
            return dict(self._grants)


_GATE: FairGate | None = None
_GATE_LOCK = threading.Lock()


def _gate() -> FairGate:
    global _GATE
    if _GATE is None:
        with _GATE_LOCK:
            if _GATE is None:
                _GATE = FairGate()
    return _GATE


@contextmanager
def dispatch_slot():
    """Gate one MRTask driver dispatch under the tenant fair-share
    (parallel/mrtask.py `_dispatch`). Free when the
    H2O_TPU_WORKLOAD_DISPATCH_SLOTS knob is 0 — one int read on the
    single-tenant default path."""
    from ..utils import knobs

    slots = knobs.get_int("H2O_TPU_WORKLOAD_DISPATCH_SLOTS")
    if slots <= 0:
        yield
        return
    from . import tenants

    name = tenants.current()
    gate = _gate()
    gate.acquire(name, slots, tenants.weight(name))
    try:
        yield
    finally:
        gate.release()


def _reset_for_tests() -> None:
    global _GATE
    with _GATE_LOCK:
        _GATE = None
