"""h2o_tpu.workload — the multi-tenant scheduler tier.

Training, serving and ingest share one process group; this package
makes them share it under admission control: tenant quotas debiting
the one reservation ledger (`workload/tenants.py`), weighted fair-share
dispatch with priority lanes and chunk-boundary preemption
(`workload/manager.py`), and a deterministic MRTask dispatch gate
(`workload/fairshare.py`). Surface: `GET/POST /3/Workload`, the
`workload.*` metrics, per-tenant `h2o_tpu_tenant_*` Prometheus lines
and the `workload.preempt` failpoint.
"""

from . import fairshare, tenants  # noqa: F401
from .manager import (  # noqa: F401
    WorkloadAdmissionError,
    WorkloadManager,
    frame_cost,
    manager,
    note_serving_pressure,
    snapshot,
    submit,
)
