# h2o_r — R client for the h2o_tpu REST API (h2o-r analog).
#
# Mirrors the reference's R client surface (`h2o-r/h2o-package/R/
# {connection,frame,models}.R`): the same versioned JSON endpoints and the
# same rapids protocol the Python client speaks. Base-R only (no httr/jsonlite
# hard dependency — jsonlite used when available, else a minimal parser for
# the subset of JSON the server emits).
#
# NOTE: the build image ships no R runtime, so this client is source-shipped
# and exercised against the same endpoints the tested Python client drives;
# the wire protocol is covered by tests/test_rest_api.py.

.h2o <- new.env()

.h2o.json <- function(txt) {
  if (requireNamespace("jsonlite", quietly = TRUE))
    return(jsonlite::fromJSON(txt, simplifyVector = FALSE))
  stop("jsonlite is required for the R client")
}

.h2o.request <- function(method, path, body = NULL, params = NULL,
                         upload = NULL, raw_text = FALSE) {
  url <- paste0(get("url", envir = .h2o), path)
  if (!is.null(params)) {
    qs <- paste(mapply(function(k, v) paste0(k, "=", utils::URLencode(
      as.character(v), reserved = TRUE)), names(params), params),
      collapse = "&")
    url <- paste0(url, "?", qs)
  }
  h <- curl::new_handle()
  curl::handle_setopt(h, customrequest = method)
  if (!is.null(upload)) {
    # raw octet-stream push (POST /3/PostFile) — the bytes of a local file
    raw <- readBin(upload, what = "raw", n = file.info(upload)$size)
    curl::handle_setopt(h, postfieldsize = length(raw), postfields = raw)
    curl::handle_setheaders(h, "Content-Type" = "application/octet-stream")
  } else if (!is.null(body)) {
    json <- if (requireNamespace("jsonlite", quietly = TRUE))
      jsonlite::toJSON(body, auto_unbox = TRUE) else stop("jsonlite required")
    curl::handle_setopt(h, postfields = as.character(json))
    curl::handle_setheaders(h, "Content-Type" = "application/json")
  }
  auth <- mget("auth", envir = .h2o, ifnotfound = list(NULL))$auth
  if (!is.null(auth)) curl::handle_setheaders(h, "Authorization" = auth)
  resp <- curl::curl_fetch_memory(url, handle = h)
  if (raw_text && resp$status_code < 400)
    return(rawToChar(resp$content))
  payload <- .h2o.json(rawToChar(resp$content))
  if (resp$status_code >= 400)
    stop(sprintf("h2o error %d: %s", resp$status_code,
                 payload$msg %||% "request failed"))
  payload
}

`%||%` <- function(a, b) if (is.null(a)) b else a

h2o.init <- function(url = "http://127.0.0.1:54321", username = NULL,
                     password = NULL) {
  assign("url", sub("/+$", "", url), envir = .h2o)
  if (!is.null(username))
    assign("auth", paste("Basic", jsonlite::base64_enc(
      charToRaw(paste0(username, ":", password %||% "")))), envir = .h2o)
  cloud <- .h2o.request("GET", "/3/Cloud")
  message(sprintf("Connected to %s (version %s)",
                  cloud$cloud_name, cloud$version))
  invisible(cloud)
}

h2o.clusterStatus <- function() .h2o.request("GET", "/3/Cloud")
h2o.shutdown <- function(prompt = FALSE) invisible(
  .h2o.request("POST", "/3/Shutdown"))
h2o.ls <- function() sapply(
  .h2o.request("GET", "/3/Frames")$frames, function(f) f$frame_id$name)
h2o.rm <- function(key) invisible(
  .h2o.request("DELETE", paste0("/3/Frames/", key)))

.h2o.poll <- function(job) {
  if (is.null(job$job$key)) {  # synchronous route: job came back DONE
    stopifnot(job$job$status == "DONE")
    return(job$job)
  }
  key <- job$job$key$name
  repeat {
    j <- .h2o.request("GET", paste0("/3/Jobs/", key))$jobs[[1]]
    if (j$status == "DONE") return(j)
    if (j$status %in% c("FAILED", "CANCELLED"))
      stop(sprintf("job %s: %s", j$status, j$exception %||% ""))
    Sys.sleep(0.1)
  }
}

h2o.importFile <- function(path, destination_frame = NULL) {
  imp <- .h2o.request("GET", "/3/ImportFiles", params = list(path = path))
  setup <- .h2o.request("POST", "/3/ParseSetup",
                        body = list(source_frames = imp$files))
  dest <- destination_frame %||% setup$destination_frame
  job <- .h2o.request("POST", "/3/Parse",
                      body = list(source_frames = imp$files,
                                  destination_frame = dest))
  done <- .h2o.poll(job)
  structure(list(frame_id = done$dest$name), class = "H2OFrame")
}

h2o.rapids <- function(expr) .h2o.request(
  "POST", "/99/Rapids", body = list(ast = expr))

h2o.getFrame <- function(id) structure(list(frame_id = id),
                                       class = "H2OFrame")

h2o.nrow <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary"))$frames[[1]]$rows

h2o.colnames <- function(fr) sapply(
  .h2o.request("GET", paste0("/3/Frames/", fr$frame_id, "/summary")
               )$frames[[1]]$columns, function(c) c$label)

.h2o.frame_expr <- function(expr) {
  res <- h2o.rapids(expr)
  if (!is.null(res$key)) return(h2o.getFrame(res$key$name))
  res$scalar %||% res$values %||% res$string
}

h2o.mean <- function(fr, col) .h2o.frame_expr(
  sprintf("(mean (cols %s '%s') true)", fr$frame_id, col))

# model builders: h2o.gbm / h2o.randomForest / h2o.glm / h2o.kmeans /
# h2o.deeplearning — the same ModelBuilders POST the reference's R client
# sends (`h2o-r/h2o-package/R/models.R`).
.h2o.train <- function(algo, x, y, training_frame, ...) {
  body <- list(...)
  body$response_column <- y
  body$training_frame <- training_frame$frame_id
  if (!missing(x) && !is.null(x)) {
    all_cols <- h2o.colnames(training_frame)
    body$ignored_columns <- setdiff(all_cols, c(x, y))
  }
  job <- .h2o.request("POST", paste0("/3/ModelBuilders/", algo), body = body)
  done <- .h2o.poll(job)
  structure(list(model_id = done$dest$name,
                 schema = .h2o.request("GET", paste0(
                   "/3/Models/", done$dest$name))$models[[1]]),
            class = "H2OModel")
}

h2o.gbm <- function(x = NULL, y, training_frame, ...)
  .h2o.train("gbm", x, y, training_frame, ...)
h2o.randomForest <- function(x = NULL, y, training_frame, ...)
  .h2o.train("drf", x, y, training_frame, ...)
h2o.glm <- function(x = NULL, y, training_frame, ...)
  .h2o.train("glm", x, y, training_frame, ...)
h2o.deeplearning <- function(x = NULL, y, training_frame, ...)
  .h2o.train("deeplearning", x, y, training_frame, ...)
h2o.kmeans <- function(training_frame, ...) {
  job <- .h2o.request("POST", "/3/ModelBuilders/kmeans",
                      body = c(list(training_frame = training_frame$frame_id),
                               list(...)))
  done <- .h2o.poll(job)
  structure(list(model_id = done$dest$name), class = "H2OModel")
}

h2o.xgboost <- function(x = NULL, y, training_frame, ...)
  .h2o.train("xgboost", x, y, training_frame, ...)
h2o.naiveBayes <- function(x = NULL, y, training_frame, ...)
  .h2o.train("naivebayes", x, y, training_frame, ...)
h2o.coxph <- function(x = NULL, event_column, training_frame, ...)
  .h2o.train("coxph", x, event_column, training_frame, ...)

.h2o.train_unsupervised <- function(algo, training_frame, ...) {
  job <- .h2o.request("POST", paste0("/3/ModelBuilders/", algo),
                      body = c(list(training_frame = training_frame$frame_id),
                               list(...)))
  done <- .h2o.poll(job)
  structure(list(model_id = done$dest$name,
                 schema = .h2o.request("GET", paste0(
                   "/3/Models/", done$dest$name))$models[[1]]),
            class = "H2OModel")
}

h2o.isolationForest <- function(training_frame, ...)
  .h2o.train_unsupervised("isolationforest", training_frame, ...)
h2o.prcomp <- function(training_frame, k = 2, ...)
  .h2o.train_unsupervised("pca", training_frame, k = k, ...)

# -- explanation data endpoints (`h2o-r` explain.R plot verbs; headless R
#    gets the PLOT DATA — varimp bars, per-row SHAP contributions, PDP
#    curves — and draws with base graphics when a device is available) ------
h2o.varimp_plot <- function(model, num_of_features = 10) {
  vi <- h2o.varimp(model)     # column-oriented: $variable, $scaled_importance
  vars <- unlist(vi$variable)
  scaled <- as.numeric(unlist(vi$scaled_importance))
  n <- min(num_of_features, length(vars))
  data <- list(variable = vars[seq_len(n)], scaled_importance = scaled[seq_len(n)])
  if (capabilities("X11") || nzchar(Sys.getenv("DISPLAY")))
    try(barplot(rev(data$scaled_importance), names.arg = rev(data$variable),
                horiz = TRUE, main = "Variable Importance"), silent = TRUE)
  invisible(data)
}

h2o.shap_summary_plot <- function(model, newdata, top_n = 10) {
  # one scoring pass with predict_contributions=TRUE -> contributions frame
  res <- .h2o.request("POST",
                      sprintf("/3/Predictions/models/%s/frames/%s",
                              model$model_id, newdata$frame_id),
                      params = list(predict_contributions = "true"))
  contrib <- h2o.getFrame(res$predictions_frame$name)
  cols <- h2o.colnames(contrib)
  mean_abs <- sapply(setdiff(cols, "BiasTerm"), function(cn)
    .h2o.frame_expr(sprintf("(mean (abs (cols %s '%s')) true)",
                            contrib$frame_id, cn)))
  ord <- order(unlist(mean_abs), decreasing = TRUE)
  invisible(list(contributions_frame = contrib$frame_id,
                 feature = names(mean_abs)[ord][seq_len(min(top_n, length(ord)))],
                 mean_abs_contribution = unlist(mean_abs)[ord][seq_len(
                   min(top_n, length(ord)))]))
}

h2o.partialPlot <- function(model, newdata, cols, nbins = 20) {
  res <- .h2o.request("POST", "/3/PartialDependence",
                      body = list(model_id = model$model_id,
                                  frame_id = newdata$frame_id,
                                  cols = paste(cols, collapse = ","),
                                  nbins = nbins))
  res$partial_dependence_data
}

h2o.predict <- function(model, newdata) {
  res <- .h2o.request("POST", sprintf("/3/Predictions/models/%s/frames/%s",
                                      model$model_id, newdata$frame_id))
  h2o.getFrame(res$predictions_frame$name)
}

h2o.saveMojo <- function(model, path) .h2o.request(
  "GET", paste0("/3/Models/", model$model_id, "/mojo"),
  params = list(dir = path))$dir

# -- binary model persistence over the wire (`h2o-r` h2o.saveModel /
#    h2o.loadModel; the /99/Models.bin routes) --------------------------------
h2o.saveModel <- function(model, path, force = FALSE) .h2o.request(
  "GET", paste0("/99/Models.bin/", model$model_id),
  params = list(dir = path, force = tolower(as.character(force))))$dir

h2o.loadModel <- function(path) {
  res <- .h2o.request("POST", "/99/Models.bin", body = list(dir = path))
  mid <- res$models[[1]]$model_id$name
  structure(list(model_id = mid,
                 schema = .h2o.request("GET", paste0("/3/Models/", mid)
                                       )$models[[1]]),
            class = "H2OModel")
}

h2o.getModel <- function(id) structure(
  list(model_id = id,
       schema = .h2o.request("GET", paste0("/3/Models/", id))$models[[1]]),
  class = "H2OModel")

# -- file upload: as.h2o on a data.frame writes a CSV and pushes it through
#    POST /3/PostFile, then parses the upload key (h2o-r as.h2o.data.frame) --
h2o.uploadFile <- function(path, destination_frame = NULL) {
  raw <- .h2o.request("POST", "/3/PostFile",
                      params = list(filename = basename(path)),
                      upload = path)
  setup <- .h2o.request("POST", "/3/ParseSetup",
                        body = list(source_frames = list(raw$destination_frame)))
  dest <- destination_frame %||% setup$destination_frame
  job <- .h2o.request("POST", "/3/Parse",
                      body = list(source_frames = list(raw$destination_frame),
                                  destination_frame = dest))
  done <- .h2o.poll(job)
  structure(list(frame_id = done$dest$name), class = "H2OFrame")
}

as.h2o <- function(df, destination_frame = NULL) {
  tmp <- tempfile(fileext = ".csv")
  utils::write.csv(df, tmp, row.names = FALSE)
  on.exit(unlink(tmp))
  h2o.uploadFile(tmp, destination_frame = destination_frame)
}

# -- frame verbs over rapids / REST ------------------------------------------
h2o.ncol <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary")
  )$frames[[1]]$num_columns

h2o.head <- function(fr, n = 6) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id),
  params = list(row_count = n))$frames[[1]]

h2o.describe <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary"))$frames[[1]]$columns

h2o.splitFrame <- function(fr, ratios = 0.75, seed = -1) {
  res <- .h2o.request("POST", "/3/SplitFrame",
                      body = list(dataset = fr$frame_id,
                                  ratios = as.list(ratios), seed = seed))
  lapply(res$destination_frames, function(k) h2o.getFrame(k$name))
}

h2o.exportFile <- function(fr, path, force = FALSE) invisible(
  .h2o.request("POST", paste0("/3/Frames/", fr$frame_id, "/export"),
               params = list(path = path,
                             force = tolower(as.character(force)))))

h2o.varimp <- function(model)
  model$schema$output$variable_importances

h2o.confusionMatrix <- function(model)
  h2o.performance(model)$cm$table


# ============================================================================
# Round-4 growth: frame algebra, grids, AutoML, performance objects — the
# verbs the reference's runit smokes lean on (`h2o-r/h2o-package/R/frame.R`,
# `models.R`, `grid.R`, `automl.R`). Everything stays wire-level: eager
# rapids per verb (the reference's lazy AST builder collapses to the same
# requests at execution time).
# ============================================================================

# frame-returning rapids with a session-temp assignment, like the reference's
# (tmp= key expr) wrapping
.h2o.frame_op <- function(expr) {
  res <- h2o.rapids(expr)
  if (is.null(res$key)) stop("rapids did not return a frame: ", expr)
  h2o.getFrame(res$key$name)
}

.h2o.col_index <- function(fr, col) {
  if (is.numeric(col)) return(as.integer(col) - 1L)  # R is 1-based
  which(h2o.colnames(fr) == col) - 1L
}

# -- slicing: fr[rows, cols] --------------------------------------------------
`[.H2OFrame` <- function(fr, i, j, ...) {
  id <- fr$frame_id
  if (!missing(j)) {
    if (is.character(j)) {
      jj <- sapply(j, function(c) .h2o.col_index(fr, c))
    } else {
      j <- as.integer(j)
      if (any(j < 0)) {  # R drop semantics: fr[, -1] removes column 1
        if (any(j > 0)) stop("can't mix positive and negative column indices")
        j <- setdiff(seq_along(h2o.colnames(fr)), -j)
      }
      jj <- j - 1L
    }
    id <- .h2o.frame_op(sprintf("(cols %s [%s])", id,
                                paste(jj, collapse = " ")))$frame_id
  }
  if (!missing(i)) {
    if (inherits(i, "H2OFrame"))
      stop("H2OFrame logical row masks are not supported in this client; ",
           "materialize indices first (e.g. which(as.data.frame(mask)[[1]]))")
    i <- as.integer(i)
    if (any(i < 0)) {  # R drop semantics: fr[-1, ] removes row 1
      if (any(i > 0)) stop("can't mix positive and negative row indices")
      n <- h2o.nrow(h2o.getFrame(id))
      i <- setdiff(seq_len(n), -i)
    }
    ii <- i - 1L
    id <- .h2o.frame_op(sprintf("(rows %s [%s])", id,
                                paste(ii, collapse = " ")))$frame_id
  }
  h2o.getFrame(id)
}

`$.H2OFrame` <- function(fr, name) {
  if (name %in% c("frame_id", "class")) return(unclass(fr)[[name]])
  .h2o.frame_op(sprintf("(cols %s '%s')", unclass(fr)$frame_id, name))
}

# -- arithmetic / comparison on frames (Ops group generic) -------------------
.h2o.binop <- function(op, e1, e2) {
  arg <- function(e) {
    if (inherits(e, "H2OFrame")) return(e$frame_id)
    if (is.character(e)) return(paste0("'", e, "'"))  # rapids string literal
    e
  }
  .h2o.frame_op(sprintf("(%s %s %s)", op, arg(e1), arg(e2)))
}

Ops.H2OFrame <- function(e1, e2) {
  op <- switch(.Generic, "%%" = "%%", .Generic)
  if (missing(e2)) {  # unary ops: -fr, !fr
    if (op == "-") return(.h2o.binop("*", e1, -1))
    if (op == "!") return(.h2o.frame_op(sprintf("(not %s)", e1$frame_id)))
    stop("unsupported unary operator on H2OFrame: ", op)
  }
  .h2o.binop(op, e1, e2)
}

h2o.log <- function(fr) .h2o.frame_op(sprintf("(log %s)", fr$frame_id))
h2o.exp <- function(fr) .h2o.frame_op(sprintf("(exp %s)", fr$frame_id))
h2o.sqrt <- function(fr) .h2o.frame_op(sprintf("(sqrt %s)", fr$frame_id))
h2o.abs <- function(fr) .h2o.frame_op(sprintf("(abs %s)", fr$frame_id))

# -- materialization ----------------------------------------------------------
as.data.frame.H2OFrame <- function(x, ...) {
  csv <- .h2o.request("GET", "/3/DownloadDataset",
                      params = list(frame_id = x$frame_id), raw_text = TRUE)
  utils::read.csv(text = csv, stringsAsFactors = FALSE)
}

h2o.asfactor <- function(fr) .h2o.frame_op(
  sprintf("(as.factor %s)", fr$frame_id))
h2o.asnumeric <- function(fr) .h2o.frame_op(
  sprintf("(as.numeric %s)", fr$frame_id))

h2o.levels <- function(fr) {
  res <- h2o.rapids(sprintf("(levels %s)", fr$frame_id))
  if (!is.null(res$key)) {
    df <- as.data.frame(h2o.getFrame(res$key$name))
    return(df[[1]])
  }
  res$values
}

h2o.nlevels <- function(fr) length(h2o.levels(fr))

h2o.table <- function(fr) .h2o.frame_op(sprintf("(table %s)", fr$frame_id))
h2o.unique <- function(fr) .h2o.frame_op(sprintf("(unique %s)", fr$frame_id))

h2o.cbind <- function(...) {
  frs <- list(...)
  .h2o.frame_op(paste0("(cbind ", paste(sapply(frs, function(f) f$frame_id),
                                        collapse = " "), ")"))
}
h2o.rbind <- function(...) {
  frs <- list(...)
  .h2o.frame_op(paste0("(rbind ", paste(sapply(frs, function(f) f$frame_id),
                                        collapse = " "), ")"))
}

h2o.ifelse <- function(test, yes, no) {
  arg <- function(a) if (inherits(a, "H2OFrame")) a$frame_id else a
  .h2o.frame_op(sprintf("(ifelse %s %s %s)", arg(test), arg(yes), arg(no)))
}

h2o.merge <- function(x, y, all.x = FALSE, all.y = FALSE) .h2o.frame_op(
  sprintf("(merge %s %s %s %s [] [] 'auto')", x$frame_id, y$frame_id,
          tolower(as.character(all.x)), tolower(as.character(all.y))))

h2o.arrange <- function(fr, ...) {
  cols <- sapply(substitute(list(...))[-1], deparse)
  idx <- sapply(cols, function(c) .h2o.col_index(fr, c))
  .h2o.frame_op(sprintf("(sort %s [%s])", fr$frame_id,
                        paste(idx, collapse = " ")))
}

h2o.group_by <- function(data, by, ...) {
  # aggregates passed as name = "column" pairs, e.g. mean = "x1"
  aggs <- list(...)
  idx <- sapply(by, function(c) .h2o.col_index(data, c))
  agg_str <- paste(mapply(function(fn, col) sprintf(
    "\"%s\" %d \"all\"", fn, .h2o.col_index(data, col)),
    names(aggs), unlist(aggs)), collapse = " ")
  .h2o.frame_op(sprintf("(GB %s [%s] %s)", data$frame_id,
                        paste(idx, collapse = " "), agg_str))
}

h2o.quantile <- function(fr, probs = c(0.1, 0.25, 0.5, 0.75, 0.9)) {
  as.data.frame(.h2o.frame_op(sprintf(
    "(quantile %s [%s] 'interpolate')", fr$frame_id,
    paste(probs, collapse = " "))))
}

h2o.sum <- function(fr, col) .h2o.frame_expr(
  sprintf("(sumaxis (cols %s '%s') true 0)", fr$frame_id, col))
h2o.sd <- function(fr, col) .h2o.frame_expr(
  sprintf("(sd (cols %s '%s') true)", fr$frame_id, col))
h2o.var <- function(fr, col) .h2o.frame_expr(
  sprintf("(var (cols %s '%s') true)", fr$frame_id, col))
h2o.min <- function(fr, col) .h2o.frame_expr(
  sprintf("(min (cols %s '%s') true)", fr$frame_id, col))
h2o.max <- function(fr, col) .h2o.frame_expr(
  sprintf("(max (cols %s '%s') true)", fr$frame_id, col))

h2o.cut <- function(fr, breaks) .h2o.frame_op(sprintf(
  "(cut %s [%s] [] false true 3)", fr$frame_id,
  paste(breaks, collapse = " ")))

h2o.scale <- function(fr, center = TRUE, scale = TRUE) .h2o.frame_op(
  sprintf("(scale %s %s %s)", fr$frame_id,
          tolower(as.character(center)), tolower(as.character(scale))))

h2o.impute <- function(fr, column = 0, method = "mean") .h2o.frame_expr(
  sprintf("(h2o.impute %s %d '%s' 'interpolate' [] _ _)", fr$frame_id,
          if (is.character(column)) .h2o.col_index(fr, column)
          else if (column <= 0) -1L  # 0/negative = all columns (server -1)
          else as.integer(column) - 1L,  # R is 1-based
          method))

h2o.createFrame <- function(rows = 100, cols = 4, seed = -1,
                            categorical_fraction = 0.2, factors = 5,
                            missing_fraction = 0) {
  job <- .h2o.request("POST", "/3/CreateFrame",
                      body = list(rows = rows, cols = cols, seed = seed,
                                  categorical_fraction = categorical_fraction,
                                  factors = factors,
                                  missing_fraction = missing_fraction))
  done <- .h2o.poll(job)
  h2o.getFrame(done$dest$name)
}

h2o.insertMissingValues <- function(fr, fraction = 0.1, seed = -1) {
  job <- .h2o.request("POST", "/3/MissingInserter",
                      body = list(dataset = fr$frame_id, fraction = fraction,
                                  seed = seed))
  .h2o.poll(job)
  fr
}

h2o.assign <- function(fr, key) {
  .h2o.request("POST", "/99/Rapids",
               body = list(ast = sprintf("(assign %s %s)", key, fr$frame_id)))
  h2o.getFrame(key)
}

# -- grid search (`h2o-r` h2o.grid / h2o.getGrid) ----------------------------
h2o.grid <- function(algorithm, grid_id = NULL, x = NULL, y = NULL,
                     training_frame, hyper_params = list(), ...) {
  body <- list(...)
  body$response_column <- y
  body$training_frame <- training_frame$frame_id
  if (!is.null(x)) {
    all_cols <- h2o.colnames(training_frame)
    body$ignored_columns <- setdiff(all_cols, c(x, y))
  }
  body$hyper_parameters <- hyper_params
  if (!is.null(grid_id)) body$grid_id <- grid_id
  job <- .h2o.request("POST", paste0("/99/Grid/", algorithm), body = body)
  done <- .h2o.poll(job)
  h2o.getGrid(done$dest$name)
}

h2o.getGrid <- function(grid_id) {
  g <- .h2o.request("GET", paste0("/99/Grids/", grid_id))
  structure(list(grid_id = grid_id,
                 model_ids = sapply(g$model_ids, function(m) m$name),
                 summary_table = g$summary_table),
            class = "H2OGrid")
}

# -- AutoML (`h2o-r` h2o.automl) ---------------------------------------------
h2o.automl <- function(x = NULL, y, training_frame, max_models = 0,
                       max_runtime_secs = 0, nfolds = 5, seed = -1,
                       include_algos = NULL, exclude_algos = NULL,
                       project_name = NULL) {
  spec <- list(training_frame = training_frame$frame_id, response_column = y)
  if (!is.null(x)) {
    all_cols <- h2o.colnames(training_frame)
    spec$ignored_columns <- setdiff(all_cols, c(x, y))
  }
  body <- list(
    input_spec = spec,
    build_control = list(
      project_name = project_name, nfolds = nfolds,
      stopping_criteria = list(max_models = max_models,
                               max_runtime_secs = max_runtime_secs,
                               seed = seed)),
    build_models = list(include_algos = include_algos,
                        exclude_algos = exclude_algos))
  job <- .h2o.request("POST", "/99/AutoMLBuilder", body = body)
  .h2o.poll(job)
  project <- job$build_control$project_name
  lb <- .h2o.request("GET", paste0("/99/Leaderboards/", project))
  leader_id <- lb$models[[1]]$name
  structure(list(project_name = project, leaderboard = lb$table,
                 leader = h2o.getModel(leader_id)), class = "H2OAutoML")
}

h2o.get_leaderboard <- function(aml) aml$leaderboard

# -- performance objects (`h2o-r` h2o.performance on new data) ---------------
h2o.performance <- function(model, newdata = NULL,
                            metric = "training_metrics") {
  if (is.null(newdata)) {
    mm <- model$schema$output[[metric]]
  } else {
    res <- .h2o.request("POST", sprintf("/3/ModelMetrics/models/%s/frames/%s",
                                        model$model_id, newdata$frame_id))
    mm <- res$model_metrics[[1]]
  }
  structure(mm, class = "H2OModelMetrics")
}

h2o.auc <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$AUC)
  h2o.performance(obj, ...)$AUC
}
h2o.rmse <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$RMSE)
  h2o.performance(obj, ...)$RMSE
}
h2o.logloss <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$logloss)
  h2o.performance(obj, ...)$logloss
}
h2o.mse <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$MSE)
  h2o.performance(obj, ...)$MSE
}
h2o.aucpr <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$pr_auc)
  h2o.performance(obj, ...)$pr_auc
}
h2o.giniCoef <- function(obj, ...) {
  if (inherits(obj, "H2OModelMetrics")) return(obj$Gini)
  h2o.performance(obj, ...)$Gini
}
h2o.gainsLift <- function(model) h2o.performance(model)$gains_lift_table

h2o.scoreHistory <- function(model) model$schema$output$scoring_history
h2o.coef <- function(model) {
  t <- model$schema$output$coefficients_table
  stats::setNames(unlist(t$coefficients), unlist(t$names))
}
h2o.coef_norm <- function(model) {
  t <- model$schema$output$coefficients_table
  stats::setNames(unlist(t$standardized_coefficients), unlist(t$names))
}

h2o.cross_validation_models <- function(model) {
  cvs <- model$schema$output$cross_validation_models
  if (is.null(cvs)) return(NULL)
  lapply(cvs, function(m) h2o.getModel(m$name))
}

h2o.download_mojo <- function(model, path = getwd()) .h2o.request(
  "GET", paste0("/3/Models/", model$model_id, "/mojo"),
  params = list(dir = file.path(path, paste0(model$model_id, ".zip"))))$dir

h2o.import_mojo <- function(path) {
  # `h2o-r` h2o.import_mojo: a Generic model over the server-side zip
  job <- .h2o.request("POST", "/3/ModelBuilders/generic",
                      body = list(path = path))
  done <- .h2o.poll(job)
  h2o.getModel(done$dest$name)
}
