# h2o_r — R client for the h2o_tpu REST API (h2o-r analog).
#
# Mirrors the reference's R client surface (`h2o-r/h2o-package/R/
# {connection,frame,models}.R`): the same versioned JSON endpoints and the
# same rapids protocol the Python client speaks. Base-R only (no httr/jsonlite
# hard dependency — jsonlite used when available, else a minimal parser for
# the subset of JSON the server emits).
#
# NOTE: the build image ships no R runtime, so this client is source-shipped
# and exercised against the same endpoints the tested Python client drives;
# the wire protocol is covered by tests/test_rest_api.py.

.h2o <- new.env()

.h2o.json <- function(txt) {
  if (requireNamespace("jsonlite", quietly = TRUE))
    return(jsonlite::fromJSON(txt, simplifyVector = FALSE))
  stop("jsonlite is required for the R client")
}

.h2o.request <- function(method, path, body = NULL, params = NULL,
                         upload = NULL) {
  url <- paste0(get("url", envir = .h2o), path)
  if (!is.null(params)) {
    qs <- paste(mapply(function(k, v) paste0(k, "=", utils::URLencode(
      as.character(v), reserved = TRUE)), names(params), params),
      collapse = "&")
    url <- paste0(url, "?", qs)
  }
  h <- curl::new_handle()
  curl::handle_setopt(h, customrequest = method)
  if (!is.null(upload)) {
    # raw octet-stream push (POST /3/PostFile) — the bytes of a local file
    raw <- readBin(upload, what = "raw", n = file.info(upload)$size)
    curl::handle_setopt(h, postfieldsize = length(raw), postfields = raw)
    curl::handle_setheaders(h, "Content-Type" = "application/octet-stream")
  } else if (!is.null(body)) {
    json <- if (requireNamespace("jsonlite", quietly = TRUE))
      jsonlite::toJSON(body, auto_unbox = TRUE) else stop("jsonlite required")
    curl::handle_setopt(h, postfields = as.character(json))
    curl::handle_setheaders(h, "Content-Type" = "application/json")
  }
  auth <- mget("auth", envir = .h2o, ifnotfound = list(NULL))$auth
  if (!is.null(auth)) curl::handle_setheaders(h, "Authorization" = auth)
  resp <- curl::curl_fetch_memory(url, handle = h)
  payload <- .h2o.json(rawToChar(resp$content))
  if (resp$status_code >= 400)
    stop(sprintf("h2o error %d: %s", resp$status_code,
                 payload$msg %||% "request failed"))
  payload
}

`%||%` <- function(a, b) if (is.null(a)) b else a

h2o.init <- function(url = "http://127.0.0.1:54321", username = NULL,
                     password = NULL) {
  assign("url", sub("/+$", "", url), envir = .h2o)
  if (!is.null(username))
    assign("auth", paste("Basic", jsonlite::base64_enc(
      charToRaw(paste0(username, ":", password %||% "")))), envir = .h2o)
  cloud <- .h2o.request("GET", "/3/Cloud")
  message(sprintf("Connected to %s (version %s)",
                  cloud$cloud_name, cloud$version))
  invisible(cloud)
}

h2o.clusterStatus <- function() .h2o.request("GET", "/3/Cloud")
h2o.shutdown <- function(prompt = FALSE) invisible(
  .h2o.request("POST", "/3/Shutdown"))
h2o.ls <- function() sapply(
  .h2o.request("GET", "/3/Frames")$frames, function(f) f$frame_id$name)
h2o.rm <- function(key) invisible(
  .h2o.request("DELETE", paste0("/3/Frames/", key)))

.h2o.poll <- function(job) {
  key <- job$job$key$name
  repeat {
    j <- .h2o.request("GET", paste0("/3/Jobs/", key))$jobs[[1]]
    if (j$status == "DONE") return(j)
    if (j$status %in% c("FAILED", "CANCELLED"))
      stop(sprintf("job %s: %s", j$status, j$exception %||% ""))
    Sys.sleep(0.1)
  }
}

h2o.importFile <- function(path, destination_frame = NULL) {
  imp <- .h2o.request("GET", "/3/ImportFiles", params = list(path = path))
  setup <- .h2o.request("POST", "/3/ParseSetup",
                        body = list(source_frames = imp$files))
  dest <- destination_frame %||% setup$destination_frame
  job <- .h2o.request("POST", "/3/Parse",
                      body = list(source_frames = imp$files,
                                  destination_frame = dest))
  done <- .h2o.poll(job)
  structure(list(frame_id = done$dest$name), class = "H2OFrame")
}

h2o.rapids <- function(expr) .h2o.request(
  "POST", "/99/Rapids", body = list(ast = expr))

h2o.getFrame <- function(id) structure(list(frame_id = id),
                                       class = "H2OFrame")

h2o.nrow <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary"))$frames[[1]]$rows

h2o.colnames <- function(fr) sapply(
  .h2o.request("GET", paste0("/3/Frames/", fr$frame_id, "/summary")
               )$frames[[1]]$columns, function(c) c$label)

.h2o.frame_expr <- function(expr) {
  res <- h2o.rapids(expr)
  if (!is.null(res$key)) return(h2o.getFrame(res$key$name))
  res$scalar %||% res$values %||% res$string
}

h2o.mean <- function(fr, col) .h2o.frame_expr(
  sprintf("(mean (cols %s '%s') true)", fr$frame_id, col))

# model builders: h2o.gbm / h2o.randomForest / h2o.glm / h2o.kmeans /
# h2o.deeplearning — the same ModelBuilders POST the reference's R client
# sends (`h2o-r/h2o-package/R/models.R`).
.h2o.train <- function(algo, x, y, training_frame, ...) {
  body <- list(...)
  body$response_column <- y
  body$training_frame <- training_frame$frame_id
  if (!missing(x) && !is.null(x)) {
    all_cols <- h2o.colnames(training_frame)
    body$ignored_columns <- setdiff(all_cols, c(x, y))
  }
  job <- .h2o.request("POST", paste0("/3/ModelBuilders/", algo), body = body)
  done <- .h2o.poll(job)
  structure(list(model_id = done$dest$name,
                 schema = .h2o.request("GET", paste0(
                   "/3/Models/", done$dest$name))$models[[1]]),
            class = "H2OModel")
}

h2o.gbm <- function(x = NULL, y, training_frame, ...)
  .h2o.train("gbm", x, y, training_frame, ...)
h2o.randomForest <- function(x = NULL, y, training_frame, ...)
  .h2o.train("drf", x, y, training_frame, ...)
h2o.glm <- function(x = NULL, y, training_frame, ...)
  .h2o.train("glm", x, y, training_frame, ...)
h2o.deeplearning <- function(x = NULL, y, training_frame, ...)
  .h2o.train("deeplearning", x, y, training_frame, ...)
h2o.kmeans <- function(training_frame, ...) {
  job <- .h2o.request("POST", "/3/ModelBuilders/kmeans",
                      body = c(list(training_frame = training_frame$frame_id),
                               list(...)))
  done <- .h2o.poll(job)
  structure(list(model_id = done$dest$name), class = "H2OModel")
}

h2o.predict <- function(model, newdata) {
  res <- .h2o.request("POST", sprintf("/3/Predictions/models/%s/frames/%s",
                                      model$model_id, newdata$frame_id))
  h2o.getFrame(res$predictions_frame$name)
}

h2o.performance <- function(model, metric = "training_metrics")
  model$schema$output[[metric]]

h2o.auc <- function(model) h2o.performance(model)$AUC
h2o.rmse <- function(model) h2o.performance(model)$RMSE

h2o.saveMojo <- function(model, path) .h2o.request(
  "GET", paste0("/3/Models/", model$model_id, "/mojo"),
  params = list(dir = path))$dir

# -- binary model persistence over the wire (`h2o-r` h2o.saveModel /
#    h2o.loadModel; the /99/Models.bin routes) --------------------------------
h2o.saveModel <- function(model, path, force = FALSE) .h2o.request(
  "GET", paste0("/99/Models.bin/", model$model_id),
  params = list(dir = path, force = tolower(as.character(force))))$dir

h2o.loadModel <- function(path) {
  res <- .h2o.request("POST", "/99/Models.bin", body = list(dir = path))
  mid <- res$models[[1]]$model_id$name
  structure(list(model_id = mid,
                 schema = .h2o.request("GET", paste0("/3/Models/", mid)
                                       )$models[[1]]),
            class = "H2OModel")
}

h2o.getModel <- function(id) structure(
  list(model_id = id,
       schema = .h2o.request("GET", paste0("/3/Models/", id))$models[[1]]),
  class = "H2OModel")

# -- file upload: as.h2o on a data.frame writes a CSV and pushes it through
#    POST /3/PostFile, then parses the upload key (h2o-r as.h2o.data.frame) --
h2o.uploadFile <- function(path, destination_frame = NULL) {
  raw <- .h2o.request("POST", "/3/PostFile",
                      params = list(filename = basename(path)),
                      upload = path)
  setup <- .h2o.request("POST", "/3/ParseSetup",
                        body = list(source_frames = list(raw$destination_frame)))
  dest <- destination_frame %||% setup$destination_frame
  job <- .h2o.request("POST", "/3/Parse",
                      body = list(source_frames = list(raw$destination_frame),
                                  destination_frame = dest))
  done <- .h2o.poll(job)
  structure(list(frame_id = done$dest$name), class = "H2OFrame")
}

as.h2o <- function(df, destination_frame = NULL) {
  tmp <- tempfile(fileext = ".csv")
  utils::write.csv(df, tmp, row.names = FALSE)
  on.exit(unlink(tmp))
  h2o.uploadFile(tmp, destination_frame = destination_frame)
}

# -- frame verbs over rapids / REST ------------------------------------------
h2o.ncol <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary")
  )$frames[[1]]$num_columns

h2o.head <- function(fr, n = 6) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id),
  params = list(row_count = n))$frames[[1]]

h2o.describe <- function(fr) .h2o.request(
  "GET", paste0("/3/Frames/", fr$frame_id, "/summary"))$frames[[1]]$columns

h2o.splitFrame <- function(fr, ratios = 0.75, seed = -1) {
  res <- .h2o.request("POST", "/3/SplitFrame",
                      body = list(dataset = fr$frame_id,
                                  ratios = as.list(ratios), seed = seed))
  lapply(res$destination_frames, function(k) h2o.getFrame(k$name))
}

h2o.exportFile <- function(fr, path, force = FALSE) invisible(
  .h2o.request("POST", paste0("/3/Frames/", fr$frame_id, "/export"),
               params = list(path = path,
                             force = tolower(as.character(force)))))

h2o.varimp <- function(model)
  model$schema$output$variable_importances

h2o.confusionMatrix <- function(model)
  h2o.performance(model)$cm$table

h2o.logloss <- function(model) h2o.performance(model)$logloss
h2o.mse <- function(model) h2o.performance(model)$MSE
