// Native runtime: parallel MSB/LSB radix argsort for the rapids sort/merge
// path — the C++ analog of the reference's distributed radix order
// (`water/rapids/RadixOrder.java`, `SplitByMSBLocal.java`,
// `BinaryMerge.java`): keys are mapped to order-preserving uint64, sorted by
// byte-wise stable LSB radix passes, parallelized per pass with per-thread
// block histograms + global prefix offsets (the same no-CAS private-copy
// merge idea as `ScoreBuildHistogram2`'s histogram build, applied to counting
// sort buckets).
//
// Exposed via a C ABI for ctypes (no pybind11 in the image). All functions
// are argsorts: they fill `order` with a permutation of [0, n), never moving
// the caller's data.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

constexpr int kRadixBits = 8;
constexpr int kBuckets = 1 << kRadixBits;  // 256

inline int hardware_threads(int requested) {
  if (requested > 0) return requested;
  unsigned hc = std::thread::hardware_concurrency();
  return hc ? static_cast<int>(hc) : 4;
}

// One stable counting pass over byte `shift/8`, scattering idx_in -> idx_out.
// Parallel and stable: threads own contiguous input blocks; global offsets
// are (bucket-major, thread-minor) prefix sums so block order is preserved.
void radix_pass(const uint64_t* keys, const int64_t* idx_in, int64_t* idx_out,
                int64_t n, int shift, int nthreads) {
  const int64_t block = (n + nthreads - 1) / nthreads;
  std::vector<std::vector<int64_t>> hist(nthreads,
                                         std::vector<int64_t>(kBuckets, 0));

  auto count_fn = [&](int t) {
    const int64_t lo = t * block, hi = std::min<int64_t>(n, lo + block);
    auto& h = hist[t];
    for (int64_t i = lo; i < hi; ++i) {
      h[(keys[idx_in[i]] >> shift) & (kBuckets - 1)]++;
    }
  };
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nthreads; ++t) ts.emplace_back(count_fn, t);
    for (auto& th : ts) th.join();
  }

  // exclusive prefix over (bucket, thread)
  int64_t run = 0;
  for (int b = 0; b < kBuckets; ++b) {
    for (int t = 0; t < nthreads; ++t) {
      int64_t c = hist[t][b];
      hist[t][b] = run;
      run += c;
    }
  }

  auto scatter_fn = [&](int t) {
    const int64_t lo = t * block, hi = std::min<int64_t>(n, lo + block);
    auto& h = hist[t];
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t src = idx_in[i];
      idx_out[h[(keys[src] >> shift) & (kBuckets - 1)]++] = src;
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) ts.emplace_back(scatter_fn, t);
  for (auto& th : ts) th.join();
}

// Which byte positions actually vary? Skipping constant bytes is the radix
// analog of RadixOrder's column-range compression.
uint64_t key_or_xor_mask(const uint64_t* keys, int64_t n, int nthreads) {
  if (n == 0) return 0;
  const int64_t block = (n + nthreads - 1) / nthreads;
  std::vector<uint64_t> acc(nthreads, 0);
  auto fn = [&](int t) {
    const int64_t lo = t * block, hi = std::min<int64_t>(n, lo + block);
    uint64_t m = 0;
    const uint64_t first = keys[0];
    for (int64_t i = lo; i < hi; ++i) m |= keys[i] ^ first;
    acc[t] = m;
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) ts.emplace_back(fn, t);
  for (auto& th : ts) th.join();
  uint64_t m = 0;
  for (uint64_t a : acc) m |= a;
  return m;
}

}  // namespace

extern "C" {

// Stable argsort of uint64 keys (order-preserving transforms applied by the
// Python caller). `order` must hold n int64; used as both scratch and result.
void h2otpu_radix_argsort_u64(const uint64_t* keys, int64_t n, int64_t* order,
                              int nthreads) {
  nthreads = hardware_threads(nthreads);
  std::vector<int64_t> tmp(n);
  int64_t* a = order;
  int64_t* b = tmp.data();
  for (int64_t i = 0; i < n; ++i) a[i] = i;

  const uint64_t varying = key_or_xor_mask(keys, n, nthreads);
  for (int shift = 0; shift < 64; shift += kRadixBits) {
    if (((varying >> shift) & (kBuckets - 1)) == 0) continue;  // constant byte
    radix_pass(keys, a, b, n, shift, nthreads);
    std::swap(a, b);
  }
  if (a != order) std::memcpy(order, a, sizeof(int64_t) * n);
}

// Stable argsort refinement: re-sorts an EXISTING permutation by new keys
// (stable ⇒ prior key order is the tiebreak). This is the lexsort building
// block: apply from least-significant key column to most.
void h2otpu_radix_refine_u64(const uint64_t* keys, int64_t n, int64_t* order,
                             int nthreads) {
  nthreads = hardware_threads(nthreads);
  std::vector<int64_t> tmp(n);
  int64_t* a = order;
  int64_t* b = tmp.data();
  const uint64_t varying = key_or_xor_mask(keys, n, nthreads);
  for (int shift = 0; shift < 64; shift += kRadixBits) {
    if (((varying >> shift) & (kBuckets - 1)) == 0) continue;
    radix_pass(keys, a, b, n, shift, nthreads);
    std::swap(a, b);
  }
  if (a != order) std::memcpy(order, a, sizeof(int64_t) * n);
}

// Gather: out[i] = keys[order[i]] — parallel permutation apply, used between
// lexsort passes and by the merge to materialize sorted key columns.
void h2otpu_gather_u64(const uint64_t* keys, const int64_t* order, int64_t n,
                       uint64_t* out, int nthreads) {
  nthreads = hardware_threads(nthreads);
  const int64_t block = (n + nthreads - 1) / nthreads;
  auto fn = [&](int t) {
    const int64_t lo = t * block, hi = std::min<int64_t>(n, lo + block);
    for (int64_t i = lo; i < hi; ++i) out[i] = keys[order[i]];
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) ts.emplace_back(fn, t);
  for (auto& th : ts) th.join();
}

}  // extern "C"
