"""Headline benchmark: GBM, HIGGS-shaped (11M rows x 28 features), 100 trees.

The north-star target (BASELINE.md): beat XGBoost `gpu_hist` on one A100 —
accepted band 15-37 s for 100 trees on HIGGS
(`compareBenchmarksStage.groovy:188-191`) — with no GPU in the loop.
vs_baseline = our_seconds / 26 (the gpu band midpoint); < 1.0 beats it.

Two cadences are measured and reported:
- ``score_once_s``   — score once at the end (one chunk), the headline value;
- ``cadence10_s``    — score_tree_interval=10 (metrics every 10 trees), the
  reference-CI-like cadence, so the scoring overhead is on the record.

The dataset is synthesized HIGGS-shaped data (the real HIGGS file is not in
the image; rows x cols x dtype match, which is what the histogram engine's
cost depends on).

Env overrides: H2O_TPU_BENCH_ROWS, H2O_TPU_BENCH_TREES (quick smoke runs),
H2O_TPU_BENCH_SKIP_CADENCE=1 (headline number only).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

GPU_BAND = (15.0, 37.0)   # A100 gpu_hist, 100 trees (the north star)
BASELINE_S = 26.0         # gpu band midpoint
CPU_50_BAND = (72.0, 77.0)  # reference CPU CI band, 50 trees (r1 metric)


def main():
    nrow = int(os.environ.get("H2O_TPU_BENCH_ROWS", 11_000_000))
    ntrees = int(os.environ.get("H2O_TPU_BENCH_TREES", 100))

    import jax
    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import T_CAT, Vec
    from h2o_tpu.models.gbm import GBM, GBMParameters

    ncol = 28
    rng = np.random.default_rng(42)
    # HIGGS: 28 continuous physics features, binary response.
    cols = {}
    latent = rng.normal(size=nrow).astype(np.float32)
    for j in range(ncol):
        mix = 0.3 if j % 3 == 0 else 0.0
        cols[f"f{j}"] = (rng.normal(size=nrow).astype(np.float32)
                         + mix * latent).astype(np.float32)
    logits = latent + 0.5 * cols["f0"] - 0.25 * cols["f3"]
    y = (rng.random(nrow) < 1 / (1 + np.exp(-logits))).astype(np.int32)

    fr = Frame.from_dict(cols)
    fr.add("response", Vec.from_numpy(y.astype(np.float32), type=T_CAT,
                                      domain=["b", "s"]))

    def run(interval: int, warm_trees: int):
        """Warm-compile the chunk-length program with a short train, then
        time the full train. The train-fn cache keys on the CHUNK length
        (score_tree_interval), so a warm-up of `warm_trees` trees at the same
        interval serves the full run with zero recompilation."""
        params = GBMParameters(training_frame=fr, response_column="response",
                               ntrees=ntrees, max_depth=5, nbins=20,
                               learn_rate=0.1, seed=42,
                               score_tree_interval=interval)
        GBM(params.clone(ntrees=warm_trees)).train_model()
        t0 = time.time()
        model = GBM(params).train_model()
        return time.time() - t0, model

    # headline: one chunk, score at the end
    t_once, model = run(interval=ntrees, warm_trees=ntrees)
    auc = model.output.training_metrics.auc

    # reference-like cadence: metrics every 10 trees. The warm-up is a FULL
    # run: the first full-length chunked train in a process measured ~4s
    # slower than every later one (allocator/tunnel warm-up), and the
    # reference bands are warm-JVM numbers.
    t_cad = None
    if not os.environ.get("H2O_TPU_BENCH_SKIP_CADENCE") and ntrees >= 20:
        iv = 10
        while ntrees % iv:  # uniform chunks: no remainder-chunk recompile
            iv -= 1
        t_cad, _ = run(interval=iv, warm_trees=ntrees)

    print(json.dumps({
        "metric": "gbm_higgs11m_100trees_train_wall",
        "value": round(t_once, 3),
        "unit": "s",
        "vs_baseline": round(t_once / BASELINE_S, 4),
        "detail": {"rows": nrow, "cols": ncol, "ntrees": ntrees,
                   "score_once_s": round(t_once, 3),
                   "cadence10_s": None if t_cad is None else round(t_cad, 3),
                   "train_auc": None if auc is None else round(float(auc), 4),
                   "baseline_band_s": list(GPU_BAND),
                   "baseline": "xgboost gpu_hist A100 100-tree band midpoint",
                   "cpu_band_50trees_s": list(CPU_50_BAND),
                   "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
