"""Multi-workload benchmark artifact (the compareBenchmarksStage analog).

Headline: GBM, HIGGS-shaped (11M rows x 28 features), 100 trees. North-star
target (BASELINE.md): beat XGBoost `gpu_hist` on one A100 — accepted band
15-37 s (`compareBenchmarksStage.groovy:188-191`) — with no GPU in the loop.
vs_baseline = our_seconds / 26 (the gpu band midpoint); < 1.0 beats it.

The driver contract is ONE JSON line; the GBM headline is the metric and
every other workload rides in ``detail.workloads`` with its own reference
band and ratio, so all README band claims are driver-recorded, not prose:

- ``glm_irlsm``  — same-shape binomial GLM, IRLSM       (band 65-73 s)
- ``glm_cod``    — same fit, solver=COORDINATE_DESCENT  (band 47-54 s)
- ``sort``       — rapids sort, 100M x 2                (band  8-14 s)
- ``merge``      — 100M x 2 join against 1M keys        (band 25-37 s)

GBM reports BOTH cadences (score once / score_tree_interval=10) and, for
each, the COLD first-run wall next to the warm steady-state: the first
full-length chunked train in a process measured ~4 s slower than every
later one (allocator/tunnel warm-up — the reference bands are warm-JVM
numbers, but the cold number is on the record).

Each workload's record is ALSO appended to a JSONL sidecar
(`BENCH_partial.jsonl`, H2O_TPU_BENCH_SIDECAR overrides) the moment it
completes, so a crash/OOM mid-run leaves every finished workload's numbers
on disk.

The ``binned_store`` leg trains the same airlines-width GBM from the f32
stacked matrix and from the chunk store's int8/int16 binned view
(`frame/chunks.py`) and records the peak training-matrix bytes of each —
the >= 3x reduction acceptance number lives in the sidecar, not in prose.

The ``serving`` leg drives the online scoring runtime (`h2o_tpu/serving/`)
over the real HTTP surface: K concurrent single-row client threads vs the
sequential single-row loop, recording p50/p95/p99 latency, rows/s, batch
occupancy and the recompile/rejection counters.

Env overrides: H2O_TPU_BENCH_ROWS, H2O_TPU_BENCH_TREES,
H2O_TPU_BENCH_SORT_ROWS, H2O_TPU_BENCH_AIRLINES_ROWS,
H2O_TPU_BENCH_BINNED_ROWS, H2O_TPU_BENCH_SERVING_REQS,
H2O_TPU_BENCH_SERVING_THREADS, H2O_TPU_BENCH_WORKLOADS (comma list,
default all), H2O_TPU_BENCH_SKIP_CADENCE=1, H2O_TPU_BENCH_SIDECAR.
"""

from __future__ import annotations

import gc
import json
import os
import time

import numpy as np

GPU_BAND = (15.0, 37.0)     # A100 gpu_hist, 100 trees (the north star)
BASELINE_S = 26.0           # gpu band midpoint
CPU_50_BAND = (72.0, 77.0)  # reference CPU CI band, 50 trees (r1 metric)
GLM_BAND = (65.0, 73.0)     # reference GLM binomial CI band
COD_BAND = (47.0, 54.0)     # reference GLM COORDINATE_DESCENT band
SORT_BAND = (8.0, 14.0)     # reference radix sort band, 100M x 2
MERGE_BAND = (25.0, 37.0)   # reference merge band, 100M x 2 vs 1M keys
GAM_BAND = (150.0, 173.0)   # reference GAM higgs IRLSM band
                            # (compareBenchmarksStage.groovy:139-147)
RULEFIT_BAND = (22.0, 27.0)  # reference RuleFit higgs RULES_AND_LINEAR
                            # depth 3 / 3 rules (groovy:314-318)


def _mid(band):
    return (band[0] + band[1]) / 2.0


def _higgs_frame(nrow: int):
    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import T_CAT, Vec

    ncol = 28
    rng = np.random.default_rng(42)
    cols = {}
    latent = rng.normal(size=nrow).astype(np.float32)
    for j in range(ncol):
        mix = 0.3 if j % 3 == 0 else 0.0
        cols[f"f{j}"] = (rng.normal(size=nrow).astype(np.float32)
                         + mix * latent).astype(np.float32)
    logits = latent + 0.5 * cols["f0"] - 0.25 * cols["f3"]
    y = (rng.random(nrow) < 1 / (1 + np.exp(-logits))).astype(np.int32)
    fr = Frame.from_dict(cols)
    fr.add("response", Vec.from_numpy(y.astype(np.float32), type=T_CAT,
                                      domain=["b", "s"]))
    return fr


def _airlines_frame(nrow: int):
    """Airlines-116M-shaped frame: the north-star's second leg
    (`BASELINE.json` "Airlines-116M train-to-AUC"; reference CI config
    `compareBenchmarksStage.groovy:165-177`). Mixed types with REAL
    categorical cardinalities — hub-skewed Origin/Dest (300 airports),
    22 carriers, calendar columns — and a response wired through per-level
    categorical effects so SET splits are what earns the AUC."""
    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import T_CAT, Vec

    rng = np.random.default_rng(116)
    n_air, n_car = 300, 22
    # hub concentration: a few airports carry most flights (Zipf-ish)
    p_air = 1.0 / (np.arange(n_air) + 5.0)
    p_air /= p_air.sum()
    origin = rng.choice(n_air, size=nrow, p=p_air).astype(np.int16)
    dest = rng.choice(n_air, size=nrow, p=p_air).astype(np.int16)
    carrier = rng.integers(0, n_car, nrow).astype(np.int8)
    year = rng.integers(0, 22, nrow).astype(np.int8)
    month = rng.integers(0, 12, nrow).astype(np.int8)
    dom = rng.integers(0, 31, nrow).astype(np.int8)
    dow = rng.integers(0, 7, nrow).astype(np.int8)
    deptime = (rng.integers(5, 24, nrow) * 100
               + rng.integers(0, 60, nrow)).astype(np.float32)
    dist = np.exp(rng.normal(6.5, 0.8, nrow)).astype(np.float32)

    air_eff = rng.normal(0, 0.6, n_air)
    car_eff = rng.normal(0, 0.4, n_car)
    mon_eff = rng.normal(0, 0.3, 12)
    logit = (air_eff[origin] + 0.7 * air_eff[dest] + car_eff[carrier]
             + mon_eff[month] + 0.6 * np.sin(deptime / 2400 * 2 * np.pi)
             + 0.2 * (dist / 1000.0) - 0.4)
    y = (rng.random(nrow) < 1 / (1 + np.exp(-logit))).astype(np.float32)

    def cat(codes, domain):
        return Vec.from_numpy(codes.astype(np.float32), type=T_CAT,
                              domain=list(domain))

    fr = Frame(
        ["Year", "Month", "DayofMonth", "DayOfWeek", "UniqueCarrier",
         "Origin", "Dest", "CRSDepTime", "Distance"],
        [cat(year, [str(1987 + i) for i in range(22)]),
         cat(month, [str(i + 1) for i in range(12)]),
         cat(dom, [str(i + 1) for i in range(31)]),
         cat(dow, [str(i + 1) for i in range(7)]),
         cat(carrier, [f"C{i:02d}" for i in range(n_car)]),
         cat(origin, [f"A{i:03d}" for i in range(n_air)]),
         cat(dest, [f"A{i:03d}" for i in range(n_air)]),
         Vec.from_numpy(deptime), Vec.from_numpy(dist)])
    fr.add("IsDepDelayed", cat(y, ["NO", "YES"]))
    return fr


def bench_airlines(nrow: int, ntrees: int) -> dict:
    """GBM train-to-AUC at Airlines scale: 100 trees over 7 categorical
    (SET splits, nbins_cats) + 2 numeric columns. The raw frame spills
    through the Cleaner once the binned matrix is resident (116M rows of
    frame + binned + working columns exceed one chip's HBM).

    Since PR 12 this is also the pipelined-training scoreboard: the leg
    trains the pipelined default (H2O_TPU_PIPELINE=1) cold + warm, then
    the synchronous oracle (=0) warm, and records the speedup, the
    forest/prediction BIT-parity flag, the warm run's uncached compile
    count, and the sampled ``gbm.pipeline.overlap_ratio`` gauge —
    acceptance: parity true, >= 1.25x, 0 uncached steady-state compiles."""
    import gc as _gc

    import jax
    import numpy as np

    from h2o_tpu.backend.memory import CLEANER, hbm_stats
    from h2o_tpu.models.gbm import GBM, GBMParameters
    from h2o_tpu.utils import compilemeter, knobs, telemetry

    t0 = time.time()
    fr = _airlines_frame(nrow)
    gen_s = round(time.time() - t0, 2)
    import jax.numpy as jnp

    t0 = time.time()
    jax.device_get([jnp.sum(v.data) for v in fr.vecs if v.data is not None])
    h2d_s = round(time.time() - t0, 2)

    params = GBMParameters(training_frame=fr, response_column="IsDepDelayed",
                           ntrees=ntrees, max_depth=5, nbins=20, seed=42,
                           learn_rate=0.1, score_tree_interval=ntrees)

    def train():
        t0 = time.time()
        m = GBM(params).train_model()  # drains device arrays on return
        return m, time.time() - t0

    prev = knobs.raw("H2O_TPU_PIPELINE")
    try:
        os.environ["H2O_TPU_PIPELINE"] = "1"
        model, wall_cold = train()            # compile + allocator warm-up
        with compilemeter.scoped() as sc:
            model, wall = train()             # the steady-state headline
        uncached = sc.uncached
        os.environ["H2O_TPU_PIPELINE"] = "0"
        # the oracle pays its own cold trace+compile first, so the
        # recorded speedup is warm-vs-warm, never compile wall (review
        # catch: the sync program is a fresh trace in this process)
        sync_model, _ = train()
        sync_model, wall_sync = train()
    finally:
        if prev is None:
            os.environ.pop("H2O_TPU_PIPELINE", None)
        else:
            os.environ["H2O_TPU_PIPELINE"] = prev
    parity = all(
        bool(np.array_equal(np.asarray(model.forest[k]),
                            np.asarray(sync_model.forest[k])))
        for k in ("feat", "thr", "nanL", "val", "gain", "catd"))
    Xs = model.adapt_frame(fr)
    parity = parity and bool(np.array_equal(
        np.asarray(model.score0(Xs)), np.asarray(sync_model.score0(Xs))))
    del Xs
    overlap = telemetry.snapshot().get("gbm.pipeline.overlap_ratio",
                                       {}).get("value")
    auc = model.output.training_metrics.auc
    stats = hbm_stats() or {}
    out = {"wall_s": round(wall, 3), "wall_cold_s": round(wall_cold, 3),
           "wall_sync_s": round(wall_sync, 3),
           "pipeline_speedup_x": round(wall_sync / max(wall, 1e-9), 3),
           "forest_parity": parity,
           "uncached_compiles_warm": uncached,
           "overlap_ratio": overlap,
           "train_auc": round(float(auc), 4),
           "rows": nrow, "gen_s": gen_s, "h2d_s": h2d_s,
           "cleaner_spills": CLEANER.spills,
           "hbm_peak_bytes": stats.get("peak_bytes_in_use"),
           "note": ("train-to-AUC north-star leg + pipelined-training "
                    "scoreboard; acceptance: forest_parity true, "
                    "pipeline_speedup_x >= 1.25, uncached_compiles_warm "
                    "== 0. no reference band at 116M — airlines-10m CPU "
                    "band is 54-78 s (x11.6 rows)")}
    del model, sync_model, fr
    _gc.collect()
    return out


def bench_binned_store(nrow: int, ntrees: int) -> dict:
    """Airlines-width binned-storage leg: the SAME GBM trained from the f32
    stacked matrix and from the chunk store's int8/int16 binned view
    (`frame/chunks.py`), recording each mode's training-matrix bytes and
    wall. The acceptance bar is a >= 3x peak-matrix-bytes reduction vs the
    stacked path (raw f32 + int32 binned codes) — measured, not derived:
    the byte counts come from `gbm.LAST_TRAIN_MATRIX_BYTES`, which the
    builder fills from the live device arrays."""
    import gc as _gc

    from h2o_tpu.backend.memory import hbm_stats
    from h2o_tpu.models import gbm as gbm_mod
    from h2o_tpu.models.gbm import GBM, GBMParameters

    from h2o_tpu.utils import knobs

    fr = _airlines_frame(nrow)
    prev = knobs.raw("H2O_TPU_BINNED_STORE")
    modes: dict = {}
    try:
        for mode, env in (("stacked_f32", "0"), ("binned", "1")):
            os.environ["H2O_TPU_BINNED_STORE"] = env
            p = GBMParameters(training_frame=fr,
                              response_column="IsDepDelayed",
                              ntrees=ntrees, max_depth=5, nbins=20, seed=42,
                              learn_rate=0.1, score_tree_interval=ntrees)
            t0 = time.time()
            model = GBM(p).train_model()
            stats = hbm_stats() or {}
            modes[mode] = {
                "wall_s": round(time.time() - t0, 3),
                "train_auc": round(float(model.output.training_metrics.auc),
                                   4),
                "matrix": dict(gbm_mod.LAST_TRAIN_MATRIX_BYTES),
                "hbm_peak_bytes": stats.get("peak_bytes_in_use"),
            }
            del model
            _gc.collect()
    finally:
        if prev is None:
            os.environ.pop("H2O_TPU_BINNED_STORE", None)
        else:
            os.environ["H2O_TPU_BINNED_STORE"] = prev
    stacked = modes["stacked_f32"]["matrix"]
    binned = modes["binned"]["matrix"]
    peak_stacked = stacked["raw_bytes"] + stacked["binned_bytes"]
    peak_binned = binned["raw_bytes"] + binned["binned_bytes"]
    del fr
    _gc.collect()
    return {"rows": nrow, "ntrees": ntrees,
            "peak_matrix_bytes_stacked": peak_stacked,
            "peak_matrix_bytes_binned": peak_binned,
            "reduction_x": round(peak_stacked / max(peak_binned, 1), 2),
            "binned_dtype": binned["binned_dtype"],
            "auc_delta": round(modes["binned"]["train_auc"]
                               - modes["stacked_f32"]["train_auc"], 6),
            "modes": modes,
            "note": ("airlines-width chunk-store leg; acceptance: "
                     "reduction_x >= 3 and auc_delta == 0 (bit-equal "
                     "forests)")}


def bench_recovery(nrow: int, ntrees: int) -> dict:
    """Preemption-proof training leg: the SAME GBM trained (a) plain,
    (b) with auto-recovery checkpoints at EVERY chunk boundary (worst-case
    cadence — production uses the wall-clock interval knob), and (c) killed
    mid-train by a deterministic failpoint and resumed to completion.

    Records checkpoint write overhead as a % of train wall (acceptance:
    < 5% even at per-boundary cadence; the write accounting comes from
    TrainingRecovery.writes/write_s, not a wall delta, so run-to-run noise
    can't fake a pass), the resume-to-parity wall, and whether the resumed
    forest + predictions are BIT-equal to the uninterrupted run."""
    import shutil
    import tempfile

    import numpy as np

    from h2o_tpu.models.gbm import GBM, GBMParameters
    from h2o_tpu.models.model_base import resume_training
    from h2o_tpu.utils import failpoints, knobs

    fr = _higgs_frame(nrow)
    interval = max(ntrees // 5, 1)  # ~5 checkpoint boundaries

    def params(**kw):
        return GBMParameters(training_frame=fr, response_column="response",
                             ntrees=ntrees, max_depth=5, nbins=20,
                             learn_rate=0.1, seed=42,
                             score_tree_interval=interval, **kw)

    # (a) uninterrupted baseline
    t0 = time.time()
    base = GBM(params()).train_model()
    base_wall = time.time() - t0
    base_pred = np.asarray(base.score0(base.adapt_frame(fr)))

    # (b) checkpointing at every boundary
    rdir = tempfile.mkdtemp(prefix="h2o_tpu_bench_rec_")
    prev = knobs.raw("H2O_TPU_CHECKPOINT_SECS")
    os.environ["H2O_TPU_CHECKPOINT_SECS"] = "0"
    try:
        builder = GBM(params(auto_recovery_dir=rdir))
        t0 = time.time()
        ck = builder.train_model()
        ck_wall = time.time() - t0
        rec = builder._recovery
        writes, write_s = ((rec.writes, rec.write_s) if rec is not None
                           else (0, 0.0))
        ck_parity = bool(np.array_equal(
            base_pred, np.asarray(ck.score0(ck.adapt_frame(fr)))))
        shutil.rmtree(rdir, ignore_errors=True)

        # (c) kill at the middle boundary, resume to parity
        rdir2 = tempfile.mkdtemp(prefix="h2o_tpu_bench_rec_")
        failpoints.reset()
        failpoints.arm("train.gbm.chunk",
                       f"raise(preempt)@{max(ntrees // interval // 2, 2)}")
        killed_wall = time.time()
        killed = False
        try:
            GBM(params(auto_recovery_dir=rdir2)).train_model()
        except failpoints.InjectedPreemption:
            killed = True
        killed_wall = time.time() - killed_wall
        failpoints.reset()
        if killed:
            t0 = time.time()
            resumed = resume_training(rdir2)
            resume_wall = time.time() - t0
            resume_parity = bool(np.array_equal(
                base_pred,
                np.asarray(resumed.score0(resumed.adapt_frame(fr)))))
        else:
            # failpoint never fired (too few boundaries for the armed hit):
            # nothing to resume — record it instead of crashing the leg
            resume_wall = 0.0
            resume_parity = None
        shutil.rmtree(rdir2, ignore_errors=True)
    finally:
        failpoints.reset()
        if prev is None:
            os.environ.pop("H2O_TPU_CHECKPOINT_SECS", None)
        else:
            os.environ["H2O_TPU_CHECKPOINT_SECS"] = prev
        del fr
        gc.collect()

    return {"rows": nrow, "ntrees": ntrees, "interval": interval,
            "train_wall_s": round(base_wall, 3),
            "ckpt_train_wall_s": round(ck_wall, 3),
            "ckpt_writes": writes,
            "ckpt_write_s": round(write_s, 3),
            "ckpt_overhead_pct": round(100.0 * write_s / max(ck_wall, 1e-9),
                                       3),
            "ckpt_bit_parity": ck_parity,
            "killed": killed,
            "killed_wall_s": round(killed_wall, 3),
            "resume_wall_s": round(resume_wall, 3),
            "resume_bit_parity": resume_parity,
            "note": ("auto-recovery at EVERY boundary (worst case); "
                     "acceptance: ckpt_overhead_pct < 5 and "
                     "resume_bit_parity true")}


def bench_workload(nrow: int, n_tenants: int) -> dict:
    """Multi-tenant scheduler leg: N tenants × (ingest + train + score)
    contending for 2 managed slots under weighted fair-share dispatch,
    with a failpoint-injected chunk-boundary preemption (auto-resumed by
    the maintenance thread) and one injected shed decision. Records
    per-tenant ingest/train walls, scoring p99, queue-wait burn and
    preemption counts — the numbers the multi-tenant acceptance bands
    gate on (all tenants complete, preemption observed and healed)."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from h2o_tpu import workload
    from h2o_tpu.backend.kvstore import STORE
    from h2o_tpu.models.gbm import GBM, GBMParameters
    from h2o_tpu.utils import failpoints, knobs
    from h2o_tpu.workload import tenants

    prev = {k: knobs.raw(k) for k in ("H2O_TPU_WORKLOAD_SLOTS",
                                      "H2O_TPU_WORKLOAD_TICK_MS",
                                      "H2O_TPU_CHECKPOINT_SECS")}
    os.environ["H2O_TPU_WORKLOAD_SLOTS"] = "2"
    os.environ["H2O_TPU_WORKLOAD_TICK_MS"] = "100"
    os.environ["H2O_TPU_CHECKPOINT_SECS"] = "0"
    names = [f"tenant{i}" for i in range(n_tenants)]
    for i, name in enumerate(names):
        tenants.configure(name, weight=float(n_tenants - i))
    failpoints.reset()
    # one boundary somewhere in the contending builds preempts — the
    # manager must park + auto-resume it while the others keep running
    failpoints.arm("workload.preempt", "raise(preempt)@3")
    mgr = workload.manager()
    per_tenant: dict = {}
    rdirs: list = []
    lock = threading.Lock()
    t_leg = time.time()

    def one_tenant(i: int, name: str) -> None:
        rec: dict = {}
        t0 = time.time()
        fr = _higgs_frame(nrow)                       # per-tenant ingest
        rec["ingest_s"] = round(time.time() - t0, 3)
        rdir = tempfile.mkdtemp(prefix=f"h2o_tpu_bench_wl_{name}_")
        with lock:
            rdirs.append(rdir)
        params = GBMParameters(
            training_frame=fr, response_column="response", ntrees=10,
            max_depth=4, nbins=20, learn_rate=0.1, seed=42 + i,
            score_tree_interval=2, auto_recovery_dir=rdir)
        t0 = time.time()
        with tenants.request_scope(
                name, "interactive" if i == 0 else "batch"):
            job = GBM(params).train(background=True)
        eid = None
        deadline = time.time() + 600
        model = None
        while time.time() < deadline:
            with mgr._lock:
                entries = mgr._live_entries() + list(mgr._done)
            if eid is None:
                mine = [e for e in entries
                        if e.job is not None and e.job.key == job.key]
                eid = mine[0].id if mine else None
            ent = next((e for e in entries if e.id == eid), None)
            if ent is not None and ent.state == "FINISHED" \
                    and ent.job.status == "DONE":
                model = STORE.get(str(ent.job.dest_key))
                rec["preemptions"] = ent.preempt_count
                break
            time.sleep(0.1)
        rec["train_wall_s"] = round(time.time() - t0, 3)
        rec["completed"] = model is not None
        if model is not None:
            adapted = model.adapt_frame(fr)
            walls = []
            for _ in range(20):
                t0 = time.time()
                np.asarray(model.score0(adapted))
                walls.append(time.time() - t0)
            rec["score_p99_ms"] = round(
                float(np.percentile(walls, 99)) * 1000.0, 3)
        with lock:
            per_tenant[name] = rec

    threads = [threading.Thread(target=one_tenant, args=(i, n),
                                name=f"bench-wl-{n}")
               for i, n in enumerate(names)]
    shed_decisions: list = []
    try:
        for t in threads:
            t.start()
        time.sleep(1.0)
        # an injected serving-pressure health snapshot mid-contention:
        # the policy picks WHICH tenant sheds (typed decision string)
        shed_decisions = mgr.shed_check(
            {"degraded": [{"check": "serving",
                           "reason": "serving-queue-saturation"}],
             "slo": {}})
        for t in threads:
            t.join(timeout=900)
    finally:
        failpoints.reset()
        mgr.stop()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for d in rdirs:
            shutil.rmtree(d, ignore_errors=True)
        gc.collect()

    snap = workload.snapshot()
    for name in names:
        if name in per_tenant:
            per_tenant[name]["burn"] = snap["tenants"][name]["burn"]
    preempts = sum(r.get("preemptions", 0) for r in per_tenant.values())
    return {"rows": nrow, "tenants": n_tenants, "slots": 2,
            "per_tenant": per_tenant,
            "total_wall_s": round(time.time() - t_leg, 3),
            "score_p99_ms_max": max(
                (r["score_p99_ms"] for r in per_tenant.values()
                 if "score_p99_ms" in r), default=None),
            "preemptions_total": preempts,
            "preemption_observed": preempts >= 1,
            "shed_decisions": shed_decisions,
            "all_completed": (len(per_tenant) == n_tenants
                              and all(r.get("completed")
                                      for r in per_tenant.values())),
            "note": ("N tenants × (ingest+train+score) over 2 managed "
                     "slots; acceptance: all_completed, "
                     "preemption_observed (injected kill auto-resumed)")}


def bench_gbm(fr, ntrees: int, skip_cadence: bool) -> dict:
    from h2o_tpu.models.gbm import GBM, GBMParameters

    def run(interval: int):
        """Cold = first full-length train at this chunk length (compile +
        allocator warm-up); warm = the immediately following identical
        train (the steady state the reference's warm-JVM bands measure).
        train_model drains the model's device arrays before returning
        (model_base.py), so the deltas measure compute, not dispatch."""
        params = GBMParameters(training_frame=fr, response_column="response",
                               ntrees=ntrees, max_depth=5, nbins=20,
                               learn_rate=0.1, seed=42,
                               score_tree_interval=interval)
        t0 = time.time()
        GBM(params).train_model()
        cold = time.time() - t0
        t0 = time.time()
        model = GBM(params).train_model()
        return cold, time.time() - t0, model

    cold_once, t_once, model = run(interval=ntrees)
    auc = model.output.training_metrics.auc
    out = {"score_once_s": round(t_once, 3),
           "score_once_cold_s": round(cold_once, 3),
           "train_auc": None if auc is None else round(float(auc), 4),
           "band_s": list(GPU_BAND),
           "vs_band_mid": round(t_once / BASELINE_S, 4)}
    if not skip_cadence and ntrees >= 20:
        iv = 10
        while ntrees % iv:  # uniform chunks: no remainder-chunk recompile
            iv -= 1
        cold_cad, t_cad, _ = run(interval=iv)
        out["cadence10_s"] = round(t_cad, 3)
        out["cadence10_cold_s"] = round(cold_cad, 3)
    return out


def bench_glm(fr, solver: str, band) -> dict:
    from h2o_tpu.models.glm import GLM, GLMParameters

    def fit():
        p = GLMParameters(training_frame=fr, response_column="response",
                          family="binomial", solver=solver, seed=42)
        t0 = time.time()
        m = GLM(p).train_model()
        return time.time() - t0, m

    cold, _ = fit()     # compile + warm-up
    warm, _ = fit()
    return {"wall_s": round(warm, 3), "cold_s": round(cold, 3),
            "band_s": list(band),
            "vs_band_mid": round(warm / _mid(band), 4)}


def bench_gam(fr) -> dict:
    """GAM higgs, solver=IRLSM (groovy band 150-173 s). The ml-benchmark
    repo's exact knot spec is not in the reference tree; this uses 3 smooth
    columns at the GAM defaults (cr basis, 8 knots) — a superset of the
    GLM-with-splines work the band times."""
    from h2o_tpu.models.gam import GAM, GAMParameters

    def fit():
        p = GAMParameters(training_frame=fr, response_column="response",
                          family="binomial", solver="IRLSM", seed=42,
                          gam_columns=["f1", "f2", "f4"])
        t0 = time.time()
        m = GAM(p).train_model()
        return time.time() - t0, m

    cold, _ = fit()
    warm, _ = fit()
    return {"wall_s": round(warm, 3), "cold_s": round(cold, 3),
            "band_s": list(GAM_BAND),
            "vs_band_mid": round(warm / _mid(GAM_BAND), 4)}


def bench_rulefit(fr) -> dict:
    """RuleFit higgs, RULES_AND_LINEAR with tree depth 3 and rule length 3
    (the groovy testcase tuple ['RULES_AND_LINEAR', 3, 3], band 22-27 s)."""
    from h2o_tpu.models.rulefit import RuleFit, RuleFitParameters

    def fit():
        p = RuleFitParameters(training_frame=fr, response_column="response",
                              model_type="rules_and_linear",
                              min_rule_length=3, max_rule_length=3, seed=42)
        t0 = time.time()
        m = RuleFit(p).train_model()
        return time.time() - t0, m

    cold, _ = fit()
    warm, _ = fit()
    return {"wall_s": round(warm, 3), "cold_s": round(cold, 3),
            "band_s": list(RULEFIT_BAND),
            "vs_band_mid": round(warm / _mid(RULEFIT_BAND), 4)}


def bench_sort(nrow: int) -> dict:
    import jax

    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import Vec
    from h2o_tpu.rapids.merge import sort as sort_fn

    rng = np.random.default_rng(7)
    fr = Frame(["k", "v"],
               [Vec.from_numpy(rng.integers(0, 1 << 30, nrow)
                               .astype(np.float32)),
                Vec.from_numpy(rng.random(nrow).astype(np.float32))])

    def once():
        t0 = time.time()
        out = sort_fn(fr, ["k"])
        jax.block_until_ready([out.vec(i).data for i in range(out.ncol)])
        dt = time.time() - t0
        # sanity: the result must actually be sorted — a mis-timed async
        # dispatch would otherwise report an impossible wall
        head = np.asarray(out.vec(0).data[:1000])
        assert np.all(np.diff(head) >= 0), "sort output not sorted"
        return dt

    once()                              # warm (compile)
    warm = min(once() for _ in range(3))
    del fr
    gc.collect()
    return {"wall_s": round(warm, 3), "band_s": list(SORT_BAND),
            "rows": nrow, "vs_band_mid": round(warm / _mid(SORT_BAND), 4)}


def bench_merge(nrow: int, nkeys: int = 1_000_000) -> dict:
    import jax

    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import Vec
    from h2o_tpu.rapids.merge import merge as merge_fn

    rng = np.random.default_rng(11)
    left = Frame(["k", "x"],
                 [Vec.from_numpy(rng.integers(0, nkeys, nrow)
                                 .astype(np.float32)),
                  Vec.from_numpy(rng.random(nrow).astype(np.float32))])
    right = Frame(["k", "y"],
                  [Vec.from_numpy(np.arange(nkeys).astype(np.float32)),
                   Vec.from_numpy(rng.random(nkeys).astype(np.float32))])
    def once():
        t0 = time.time()
        out = merge_fn(left, right)
        jax.block_until_ready([out.vec(i).data for i in range(out.ncol)])
        assert out.nrow == nrow
        return time.time() - t0

    once()                              # warm (compile)
    warm = min(once() for _ in range(2))
    del left, right
    gc.collect()
    return {"wall_s": round(warm, 3), "band_s": list(MERGE_BAND),
            "rows": nrow, "keys": nkeys,
            "vs_band_mid": round(warm / _mid(MERGE_BAND), 4)}


def bench_serving(n_reqs: int, n_threads: int) -> dict:
    """Online-scoring leg: K concurrent client threads of single-row
    requests against the micro-batched serving runtime
    (`h2o_tpu/serving/`), through the REAL HTTP surface (`api/client.py`
    serving helpers). Three numbers frame the win:

    - ``single_row_http``: 1 thread, sequential single-row requests against
      a max_wait_us=0 registration — the EasyPredict-style serving loop
      (one dispatch per row, no coalescing) over the same wire.
    - ``single_row_direct``: in-process loop over the bucket-1 compiled
      scorer, no HTTP/batcher at all — the raw dispatch-per-row floor.
    - ``concurrent``: K threads of small (8-row) requests against the
      default registration; the batcher coalesces them into ~100-row
      device calls, occupancy climbs far above 1, and rows/s is the
      headline. speedup_vs_single_row = concurrent / single_row_loop.

    The single-row loops and the concurrent fan-out drive the runtime
    in-process (client/server/batcher share one CPython process here, so
    per-request HTTP threads + the GIL would measure the stdlib server,
    not the subsystem); the HTTP surface is still exercised for real by
    this leg — registration, warm-up requests, the latency sample and the
    stats fetch all go through `api/client.py` — and its sequential
    throughput is on the record as ``single_row_http_rows_s``. Request
    latencies are client-side wall deltas around blocking calls (the
    response body IS host data — nothing async to drain). Acceptance:
    speedup >= 5x at occupancy > 1 and zero steady-state recompiles."""
    import threading

    import h2o_tpu.api as h2o
    from h2o_tpu.models.gbm import GBM, GBMParameters

    conn = h2o.init(port=54731)
    if getattr(conn, "_server", None) is None:
        # init() connect-or-spawns: a foreign server already on this port
        # would receive our registrations while the leg drives the LOCAL
        # runtime singleton — and h2o.shutdown() would kill that server
        raise RuntimeError("serving bench needs its own in-process server; "
                           "port 54731 is already serving another process")
    fr = _higgs_frame(50_000)
    model = GBM(GBMParameters(training_frame=fr, response_column="response",
                              ntrees=20, max_depth=5, nbins=20, seed=42,
                              learn_rate=0.1,
                              score_tree_interval=20)).train_model()
    feat_names = [f"f{j}" for j in range(5)]  # sparse row dicts: absent→NaN
    rng = np.random.default_rng(9)
    rows = [{n: float(v) for n, v in
             zip(feat_names, rng.normal(size=len(feat_names)))}
            for _ in range(256)]

    from h2o_tpu.serving import get_runtime

    # baseline registration: no coalescing window — the single-row loop
    # must not pay a wait that only exists to serve concurrency
    h2o.register_serving(model.key, serving_id="bench_base", max_wait_us=0)
    h2o.register_serving(model.key, serving_id="bench_serving")
    rt = get_runtime()

    # real-HTTP sample: sequential single-row requests through the client
    n_http = max(50, min(300, n_reqs // 16))
    for r in rows[:8]:
        h2o.score_rows("bench_base", r)      # connection/runtime warm-up
    t0 = time.time()
    for i in range(n_http):
        h2o.score_rows("bench_base", rows[i % len(rows)])
    http_rows_s = n_http / (time.time() - t0)

    # single-row-loop baseline: the EasyPredict-style serve loop, one
    # request (and one device call) per row, through the runtime
    n_base = max(200, min(1000, n_reqs // 4))
    t0 = time.time()
    for i in range(n_base):
        rt.score("bench_base", [rows[i % len(rows)]])
    base_rows_s = n_base / (time.time() - t0)

    rows_per_req = 8
    per_thread = max(n_reqs // n_threads, 1)
    lat: list[list[float]] = [[] for _ in range(n_threads)]

    def client(k: int):
        from h2o_tpu.serving.errors import (DeadlineExceededError,
                                            QueueFullError)

        for i in range(per_thread):
            at = (k * per_thread + i) % (len(rows) - rows_per_req)
            t1 = time.time()
            try:
                rt.score("bench_serving", rows[at:at + rows_per_req],
                         deadline_ms=10_000)
            except (QueueFullError, DeadlineExceededError):
                continue  # already tallied by the runtime's own counters
            lat[k].append(time.time() - t1)

    threads = [threading.Thread(target=client, args=(k,), daemon=True)
               for k in range(n_threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    conc_wall = time.time() - t0
    done = sum(len(ls) for ls in lat)
    conc_rows_s = done * rows_per_req / conc_wall
    all_lat = np.sort(np.concatenate([np.asarray(ls) for ls in lat]))
    if all_lat.size:
        p50, p95, p99 = (round(float(v) * 1000, 3) for v in
                         np.percentile(all_lat, (50, 95, 99)))
    else:  # every request rejected/timed out — record THAT, don't crash
        p50 = p95 = p99 = None
    snap = h2o.serving_stats("bench_serving")["bench_serving"]
    h2o.unregister_serving("bench_serving")
    h2o.unregister_serving("bench_base")
    h2o.shutdown()
    del fr
    gc.collect()
    return {
        "requests": done, "threads": n_threads,
        "rows_per_request": rows_per_req,
        "wall_s": round(conc_wall, 3),
        "rows_per_s": round(conc_rows_s, 1),
        "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
        "single_row_loop_rows_s": round(base_rows_s, 1),
        "single_row_http_rows_s": round(http_rows_s, 1),
        "speedup_vs_single_row": round(conc_rows_s / base_rows_s, 2),
        "mean_batch_occupancy": snap["mean_batch_occupancy"],
        "recompiles": snap["recompiles"],
        # the runtime counters already include every error the clients saw
        # (submit() counts before raising) — do not sum the two tallies
        "rejected": snap["rejected"],
        "timeouts": snap["timeouts"],
        "note": ("single-row-loop vs micro-batched runtime (HTTP surface "
                 "exercised; throughput legs in-process — see docstring); "
                 "acceptance: speedup >= 5x at occupancy > 1, "
                 "recompiles == 0"),
    }


_WIRE_CLIENT = '''\
import sys, threading, time

sys.path.insert(0, {repo!r})
import h2o_tpu.api.client as c

row = {{"x1": 0.5}}
n_per, n_threads = int(sys.argv[1]), int(sys.argv[2])
conn = c.H2OConnection("http://127.0.0.1:{port}")
for _ in range(10):  # connection + scorer warm-up, untimed
    conn.request("POST", "/3/Serving/score",
                 data={{"model_id": "wire", "rows": [row]}})

done = [0] * n_threads
errors = []


def worker(k):
    try:
        for _ in range(n_per):
            conn.request("POST", "/3/Serving/score",
                         data={{"model_id": "wire", "rows": [row]}})
            done[k] += 1
    except Exception as e:  # a dead worker must FAIL the leg, not
        errors.append(repr(e))  # silently inflate req/s


threads = [threading.Thread(target=worker, args=(k,))
           for k in range(n_threads)]
t0 = time.time()
for t in threads:
    t.start()
for t in threads:
    t.join()
elapsed = time.time() - t0
if errors or sum(done) != n_per * n_threads:
    print("wire client workers failed: completed %d/%d: %s"
          % (sum(done), n_per * n_threads, errors[:3]), file=sys.stderr)
    sys.exit(1)
print(sum(done) / elapsed)
'''


def bench_serving_wire(n_reqs: int) -> dict:
    """Keep-alive wire leg: sequential AND concurrent single-row HTTP
    scoring from a SUBPROCESS client (its own interpreter — an in-process
    client competes with the server for the GIL and measures contention,
    not the wire), pooled persistent connections vs one connection per
    request (``H2O_TPU_CLIENT_KEEPALIVE=0``, the pre-pool transport shape).

    The model is a tiny GLM registered with ``max_wait_us=0`` so the
    coalescing window and tree-scoring cost don't mask the wire: what's
    left per request is HTTP parse + routing + one sub-ms scorer call.
    The headline is the CONCURRENT ratio — under fleet-shaped load,
    per-request connections collapse (TCP dial + a fresh server handler
    thread per connection + TIME_WAIT churn serialize on the accept path)
    while pooled lanes ride persistent handler threads and the batcher
    coalesces across them. Acceptance: pooled >= 3x per-request req/s
    concurrent, recompiles == 0 through the whole leg."""
    import subprocess
    import sys as _sys

    import h2o_tpu.api as h2o
    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import Vec
    from h2o_tpu.models.glm import GLM, GLMParameters

    port = 54732
    conn = h2o.init(port=port)
    if getattr(conn, "_server", None) is None:
        raise RuntimeError("serving_wire bench needs its own in-process "
                           "server; port 54732 is already serving another "
                           "process")
    rng = np.random.default_rng(11)
    n = 2000
    x1 = rng.normal(size=n).astype(np.float32)
    y = (2.0 * x1 + rng.normal(scale=0.1, size=n)).astype(np.float32)
    fr = Frame(["x1", "y"], [Vec.from_numpy(x1), Vec.from_numpy(y)])
    glm = GLM(GLMParameters(training_frame=fr, response_column="y",
                            family="gaussian", seed=1)).train_model()
    h2o.register_serving(glm.key, serving_id="wire", buckets=[1, 8, 64],
                         max_wait_us=0)

    import tempfile

    script = _WIRE_CLIENT.format(
        repo=os.path.dirname(os.path.abspath(__file__)), port=port)
    fd, script_path = tempfile.mkstemp(suffix="_wire_client.py")
    with os.fdopen(fd, "w") as f:
        f.write(script)

    def run(keepalive: str, n_per: int, n_threads: int) -> float:
        env = dict(os.environ)
        env["H2O_TPU_CLIENT_KEEPALIVE"] = keepalive
        out = subprocess.run(
            [_sys.executable, script_path, str(n_per), str(n_threads)],
            capture_output=True, text=True, timeout=600, env=env)
        if out.returncode != 0:
            raise RuntimeError(f"wire client failed:\n{out.stderr[-2000:]}")
        return float(out.stdout.strip().splitlines()[-1])

    threads = 32
    seq_n = max(n_reqs // 2, 100)
    conc_per = max(n_reqs // threads, 20)
    try:
        pooled_seq = run("1", seq_n, 1)
        perreq_seq = run("0", seq_n, 1)
        pooled_conc = run("1", conc_per, threads)
        perreq_conc = run("0", conc_per, threads)
    finally:
        os.unlink(script_path)
    snap = h2o.serving_stats("wire")["wire"]
    h2o.unregister_serving("wire")
    h2o.shutdown()
    del fr
    gc.collect()
    return {
        "sequential": {
            "pooled_req_s": round(pooled_seq, 1),
            "per_request_req_s": round(perreq_seq, 1),
            "pooled_x": round(pooled_seq / perreq_seq, 2),
        },
        "concurrent": {
            "threads": threads,
            "pooled_req_s": round(pooled_conc, 1),
            "per_request_req_s": round(perreq_conc, 1),
            "pooled_x": round(pooled_conc / perreq_conc, 2),
        },
        "recompiles": snap["recompiles"],
        "note": ("subprocess client (own GIL), GLM @ max_wait_us=0 so the "
                 "wire dominates; acceptance: concurrent pooled_x >= 3 "
                 "and recompiles == 0"),
    }


_SHARDED_SCRIPT = '''\
import json, sys, time

sys.path.insert(0, {repo!r})
import jax

jax.config.update("jax_platforms", "cpu")
import hashlib

import numpy as np

import bench

nrow = int(sys.argv[1])
ntrees = int(sys.argv[2])
fr = bench._higgs_frame(nrow)
import jax.numpy as jnp

from h2o_tpu.backend.memory import CLEANER
from h2o_tpu.models import gbm as gbm_mod
from h2o_tpu.models.gbm import GBM, GBMParameters
from h2o_tpu.parallel import mesh as meshmod

jax.device_get([jnp.sum(v.data) for v in fr.vecs if v.data is not None])
t0 = time.time()
model = GBM(GBMParameters(training_frame=fr, response_column="response",
                          ntrees=ntrees, max_depth=5, nbins=20, seed=42,
                          learn_rate=0.1,
                          score_tree_interval=ntrees)).train_model()
train_wall = time.time() - t0
# forest STRUCTURE digest (split features + NA directions): must be
# BIT-equal across shard counts — the SPMD histograms must not change a
# single split decision
struct = hashlib.sha256()
for k in ("feat", "nanL"):
    struct.update(np.ascontiguousarray(np.asarray(model.forest[k])).tobytes())
# margin probe on a fixed row block: floats accumulate through psum, whose
# reduction order differs across mesh widths — the parent pins closeness
probe_rows = min(nrow, 512)
Xp = np.stack([np.nan_to_num(fr.vec(n).to_numpy()[:probe_rows])
               for n in model.output.names], axis=1).astype(np.float32)
margins = np.asarray(model._raw_f(jnp.asarray(Xp)), np.float64)
peaks = CLEANER.device_peak_bytes()
auc = model.output.training_metrics.auc
print(json.dumps({{
    "n_row_shards": int(meshmod.n_row_shards()),
    "train_wall_s": round(train_wall, 3),
    "auc": round(float(auc), 6),
    "matrix_bytes": gbm_mod.LAST_TRAIN_MATRIX_BYTES["binned_bytes"],
    "per_shard_matrix_bytes":
        gbm_mod.LAST_TRAIN_MATRIX_BYTES["per_shard_bytes"],
    "psum_bytes_per_tree":
        gbm_mod.LAST_TRAIN_MATRIX_BYTES["psum_bytes_per_tree"],
    "per_device_peak_bytes": max(peaks.values()) if peaks else 0,
    "forest_struct_sha": struct.hexdigest(),
    "probe_margins": [round(v, 10) for v in margins.tolist()],
}}))
'''


def bench_sharded(nrow: int, ntrees: int, n_shards: int = 8) -> dict:
    """Sharded leg: the SAME GBM workload at 1 vs ``n_shards`` row shards,
    each in a FRESH subprocess on an ``n_shards``-wide virtual CPU mesh
    (H2O_TPU_ROW_SHARDS is read once at mesh construction, so shard counts
    can't flip mid-process). On the record per leg: per-shard peak
    training-matrix bytes (the per-chip HBM number), the per-tree ICI psum
    payload, wall, and a forest-structure digest. Acceptance: the sharded
    leg's per-shard matrix bytes <= single-shard/n_shards + a fixed
    overhead, forest STRUCTURE bit-equal across shard counts, margins
    within reduction-order tolerance."""
    import subprocess
    import sys as _sys
    import tempfile

    script = _SHARDED_SCRIPT.format(
        repo=os.path.dirname(os.path.abspath(__file__)))
    fd, script_path = tempfile.mkstemp(suffix="_sharded.py")
    with os.fdopen(fd, "w") as f:
        f.write(script)

    def run_leg(shards: int) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [fl for fl in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in fl]
        flags.append(f"--xla_force_host_platform_device_count={n_shards}")
        env["XLA_FLAGS"] = " ".join(flags)
        env["H2O_TPU_ROW_SHARDS"] = str(shards)
        out = subprocess.run(
            [_sys.executable, script_path, str(nrow), str(ntrees)],
            capture_output=True, text=True, timeout=1800, env=env)
        if out.returncode != 0:
            raise RuntimeError(f"sharded subprocess (shards={shards}) "
                               f"failed:\n{out.stderr[-2000:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        single = run_leg(1)
        sharded = run_leg(n_shards)
    finally:
        os.unlink(script_path)
    m1 = np.asarray(single.pop("probe_margins"))
    mn = np.asarray(sharded.pop("probe_margins"))
    delta = float(np.max(np.abs(m1 - mn))) if m1.size else 0.0
    scale = float(np.max(np.abs(m1))) if m1.size else 1.0
    per_1 = single["per_shard_matrix_bytes"]
    per_n = sharded["per_shard_matrix_bytes"]
    overhead = 64 * 1024  # fixed allowance over the ideal 1/n split
    return {
        "rows": nrow,
        "ntrees": ntrees,
        "n_shards": n_shards,
        "single": single,
        "sharded": sharded,
        "per_shard_reduction_x": round(per_1 / max(per_n, 1), 2),
        "per_shard_bytes_ok": per_n <= per_1 // n_shards + overhead,
        "forest_struct_equal": (single["forest_struct_sha"]
                                == sharded["forest_struct_sha"]),
        "probe_margin_max_abs_delta": delta,
        "probe_margin_rel_delta": delta / max(scale, 1e-12),
        "note": ("same GBM at 1 vs N row shards, fresh subprocesses; "
                 "acceptance: per-shard matrix bytes <= single/N + 64KiB, "
                 "forest structure bit-equal, margins within reduction-"
                 "order ulps"),
    }


_COLDSTART_SCRIPT = '''\
import json, sys, time

sys.path.insert(0, {repo!r})
import numpy as np
import bench
from h2o_tpu.utils import compile_cache, compilemeter

# the wiring under test: a process with H2O_TPU_COMPILE_CACHE set gets the
# persistent cache from its first entry point (here: explicitly at process
# start, exactly what cluster init / deploy_entry / the first train do)
compile_cache.ensure()
compilemeter.install()

from h2o_tpu.models.gbm import GBM, GBMParameters

nrow = int(sys.argv[1])
fr = bench._higgs_frame(nrow)
import jax
import jax.numpy as jnp

jax.device_get([jnp.sum(v.data) for v in fr.vecs if v.data is not None])
t0 = time.time()
model = GBM(GBMParameters(training_frame=fr, response_column="response",
                          ntrees=20, max_depth=5, nbins=20, seed=42,
                          learn_rate=0.1,
                          score_tree_interval=20)).train_model()
train_wall = time.time() - t0
t0 = time.time()
preds = model.score0(model.adapt_frame(fr))
jax.block_until_ready(preds)
score_wall = time.time() - t0
print(json.dumps({{"train_wall_s": round(train_wall, 3),
                   "score_wall_s": round(score_wall, 3),
                   "programs": compilemeter.count(),
                   "cache_hits": compilemeter.cache_hits(),
                   "uncached_compiles": compilemeter.uncached_count()}}))
'''


def bench_cold_start(nrow: int) -> dict:
    """Cold-start leg: the SAME small GBM train+score run in two FRESH
    subprocesses sharing one persistent XLA compile-cache dir
    (`H2O_TPU_COMPILE_CACHE`, wired through `utils/compile_cache.ensure`).
    Process 1 populates the cache (every program a real compile); process 2
    must replay it — `compilemeter` separates programs-through-the-compile-
    path from real compilations via the cache-hit events, and the
    acceptance is ``warm_uncached_compiles <= 2`` with a materially lower
    first-train wall (the ROADMAP cold-start item: BENCH_r03/r04 measured
    49-94 s cold vs 10.5 s warm before the cache was wired into
    training)."""
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="h2o_tpu_bench_xla_")
    script = _COLDSTART_SCRIPT.format(
        repo=os.path.dirname(os.path.abspath(__file__)))
    fd, script_path = tempfile.mkstemp(suffix="_cold_start.py")
    with os.fdopen(fd, "w") as f:
        f.write(script)

    def run_proc() -> dict:
        env = dict(os.environ)
        env["H2O_TPU_COMPILE_CACHE"] = cache_dir
        out = subprocess.run(
            [_sys.executable, script_path, str(nrow)],
            capture_output=True, text=True, timeout=1800, env=env)
        if out.returncode != 0:
            raise RuntimeError(f"cold_start subprocess failed:\n"
                               f"{out.stderr[-2000:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        cold = run_proc()
        cache_files = len([f for f in os.listdir(cache_dir)
                           if f.endswith("-cache")])
        warm = run_proc()
    finally:
        os.unlink(script_path)
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {
        "rows": nrow,
        "cold": cold,
        "warm": warm,
        "cache_files": cache_files,
        "cold_compiles": cold["uncached_compiles"],
        "warm_uncached_compiles": warm["uncached_compiles"],
        "warm_cache_hits": warm["cache_hits"],
        "train_speedup_x": round(cold["train_wall_s"]
                                 / max(warm["train_wall_s"], 1e-9), 2),
        "note": ("two fresh processes, one warmed compile cache; "
                 "acceptance: warm_uncached_compiles <= 2 and cold "
                 "train_wall materially above warm"),
    }


def _enable_compile_cache():
    """Persistent XLA compilation cache for accelerator backends — the
    standard TPU deployment practice (and the fix for the cold-start gap:
    the first train in a fresh process pays ~25-70 s of compiles that the
    cache replays in seconds). CPU stays opt-in: jax 0.9.0's CPU executable
    serializer segfaulted once mid-suite (tests/conftest.py history).
    Override the location with H2O_TPU_COMPILE_CACHE; set it to '0' to
    disable."""
    from h2o_tpu.utils import compile_cache

    compile_cache.enable(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".xla_cache"))


class _CompileCounter:
    """Counts distinct XLA program builds (VERDICT r4 #5 asks the program
    count on the record): jax_log_compiles emits one record per program that
    reaches the compiler (persistent-cache hits included — each is one
    remote-side program load through the tunnel)."""

    def __init__(self):
        import logging

        self.count = 0

        class H(logging.Handler):
            def emit(_self, record):
                if "Compiling" in record.getMessage():
                    self.count += 1

        # no jax_log_compiles: the same records exist at DEBUG priority
        # without the flag (the flag only raises them to WARNING, which
        # would spam stderr via the root logger's lastResort handler)
        for name in ("jax._src.interpreters.pxla", "jax._src.dispatch"):
            lg = logging.getLogger(name)
            lg.setLevel(logging.DEBUG)
            lg.addHandler(H())


def _sidecar_path() -> str:
    """Per-workload crash-proof record file (H2O_TPU_BENCH_SIDECAR
    overrides): one JSON line per completed workload, flushed+fsynced the
    moment it finishes, so an OOM in the LAST workload can never erase the
    earlier ones' numbers (the round-5 BENCH crash). The file is
    APPEND-ONLY — each run opens with a ``bench_run`` header line, so a
    retry after a crash delimits a new run instead of wiping the crashed
    run's surviving records. The final stdout summary line is unchanged
    when every workload survives."""
    from h2o_tpu.utils import knobs

    return knobs.raw("H2O_TPU_BENCH_SIDECAR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_partial.jsonl")


#: sidecar record-format version — bumped when line shape changes so
#: tools/bench_gate.py and future re-anchors parse ONE documented format
#: (schema doc: README "Benchmarks" — v2 = v1 + schema_version stamps +
#: the per-leg record["programs"] program-cost delta block)
SIDECAR_SCHEMA_VERSION = 2


def _sidecar_start(header: dict) -> None:
    header = dict(header, schema_version=SIDECAR_SCHEMA_VERSION)
    with open(_sidecar_path(), "a") as f:
        f.write(json.dumps({"bench_run": header}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _emit_workload(workloads: dict, name: str, rec: dict) -> None:
    workloads[name] = rec
    with open(_sidecar_path(), "a") as f:
        f.write(json.dumps({"workload": name,
                            "schema_version": SIDECAR_SCHEMA_VERSION,
                            "record": rec}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _leg(workloads: dict, name: str, fn) -> dict:
    """Run one workload with a telemetry snapshot taken around it and embed
    the registry DELTA in the fsync'd sidecar record — every leg's numbers
    now carry compile counts, MRTask dispatch/payload totals, spill bytes
    and the HBM watermark next to its wall times (utils/telemetry.py) —
    plus the PROGRAM-COST delta: every executable the leg compiled lands
    with its XLA flops/bytes/memory figures (utils/programs.py), so a
    re-anchor records what each leg's programs cost, not just how long
    they ran."""
    from h2o_tpu.utils import programs, telemetry

    before = telemetry.snapshot()
    before_programs = programs.ids()
    rec = dict(fn())
    rec["telemetry"] = telemetry.snapshot_delta(before)
    rec["programs"] = programs.snapshot_delta(before_programs)
    _emit_workload(workloads, name, rec)
    return rec


def main():
    from h2o_tpu.utils import knobs

    nrow = knobs.get_int("H2O_TPU_BENCH_ROWS")
    ntrees = knobs.get_int("H2O_TPU_BENCH_TREES")
    sort_rows = knobs.get_int("H2O_TPU_BENCH_SORT_ROWS")
    wanted = [w.strip()
              for w in knobs.get_str("H2O_TPU_BENCH_WORKLOADS").split(",")]
    skip_cadence = knobs.get_bool("H2O_TPU_BENCH_SKIP_CADENCE")

    import jax

    _enable_compile_cache()
    compiles = _CompileCounter()
    # backend-compile events feed the telemetry registry from the first
    # leg, so every sidecar record's delta carries its compile count
    from h2o_tpu.utils import compilemeter

    compilemeter.install()
    _sidecar_start({"rows": nrow, "ntrees": ntrees, "sort_rows": sort_rows,
                    "workloads": wanted,
                    "backend": jax.default_backend()})
    workloads: dict = {}
    gbm = None
    h2d_s = None
    if {"gbm", "glm", "cod", "gam", "rulefit"} & set(wanted):
        fr = _higgs_frame(nrow)
        # flush host->device before timing anything: under the axon tunnel
        # the first DEVICE_GET otherwise absorbs remote materialization of
        # the frame. block_until_ready is NOT a barrier here (round-5
        # measurement: bur returned in 0.0 s while a subsequent device_get
        # of a scalar blocked 65 s) — only an actual host fetch drains the
        # remote pipeline, so the flush device_gets the per-column sums.
        # NOT a train cost — real TPU hosts feed HBM over PCIe/DMA; the
        # reference bands also exclude ingest. Recorded as h2d_s. With the
        # flush real, one-shot cold train measures 17 s vs 11 s warm — the
        # residual ~6 s is first-load of the ~16 cached XLA programs
        # through the tunnel.
        import jax.numpy as jnp

        t0 = time.time()
        sums = [jnp.sum(v.data) for v in fr.vecs if v.data is not None]
        jax.device_get(sums)
        h2d_s = round(time.time() - t0, 3)
        if "gbm" in wanted:
            gbm = _leg(workloads, "gbm",
                       lambda: bench_gbm(fr, ntrees, skip_cadence))
        if "glm" in wanted:
            _leg(workloads, "glm_irlsm",
                 lambda: bench_glm(fr, "IRLSM", GLM_BAND))
        if "cod" in wanted:
            _leg(workloads, "glm_cod",
                 lambda: bench_glm(fr, "COORDINATE_DESCENT", COD_BAND))
        if "gam" in wanted:
            _leg(workloads, "gam_irlsm", lambda: bench_gam(fr))
        if "rulefit" in wanted:
            _leg(workloads, "rulefit", lambda: bench_rulefit(fr))
        del fr
        gc.collect()
    if "sort" in wanted:
        _leg(workloads, "sort", lambda: bench_sort(sort_rows))
    if "merge" in wanted:
        _leg(workloads, "merge", lambda: bench_merge(sort_rows))
    if "serving" in wanted:
        _leg(workloads, "serving", lambda: bench_serving(
            knobs.get_int("H2O_TPU_BENCH_SERVING_REQS"),
            knobs.get_int("H2O_TPU_BENCH_SERVING_THREADS")))
    if "serving_wire" in wanted:
        _leg(workloads, "serving_wire", lambda: bench_serving_wire(
            knobs.get_int("H2O_TPU_BENCH_WIRE_REQS")))
    if "binned" in wanted:
        _leg(workloads, "binned_store",
             lambda: bench_binned_store(
                 knobs.get_int("H2O_TPU_BENCH_BINNED_ROWS"),
                 min(ntrees, 20)))
    if "recovery" in wanted:
        _leg(workloads, "recovery", lambda: bench_recovery(
            knobs.get_int("H2O_TPU_BENCH_RECOVERY_ROWS"),
            min(ntrees, 20)))
    if "workload" in wanted:
        _leg(workloads, "workload", lambda: bench_workload(
            knobs.get_int("H2O_TPU_BENCH_WORKLOAD_ROWS"),
            knobs.get_int("H2O_TPU_BENCH_WORKLOAD_TENANTS")))
    if "cold_start" in wanted:
        _leg(workloads, "cold_start", lambda: bench_cold_start(
            knobs.get_int("H2O_TPU_BENCH_COLDSTART_ROWS")))
    if "sharded" in wanted:
        _leg(workloads, "sharded", lambda: bench_sharded(
            knobs.get_int("H2O_TPU_BENCH_SHARDED_ROWS"), min(ntrees, 20)))
    if "airlines" in wanted:
        _leg(workloads, "airlines116m", lambda: bench_airlines(
            knobs.get_int("H2O_TPU_BENCH_AIRLINES_ROWS"), ntrees))

    t_once = gbm["score_once_s"] if gbm else None
    print(json.dumps({
        "metric": "gbm_higgs11m_100trees_train_wall",
        "value": t_once,
        "unit": "s",
        "vs_baseline": (None if t_once is None
                        else round(t_once / BASELINE_S, 4)),
        "detail": {"rows": nrow, "cols": 28, "ntrees": ntrees,
                   "h2d_s": h2d_s,
                   "xla_programs_built": compiles.count,
                   "baseline": "xgboost gpu_hist A100 100-tree band midpoint",
                   "cpu_band_50trees_s": list(CPU_50_BAND),
                   "backend": jax.default_backend(),
                   "workloads": workloads},
    }))


if __name__ == "__main__":
    main()
