"""Headline benchmark: GBM, HIGGS-shaped (11M rows x 28 features), 50 trees.

Mirrors the reference's nightly CI gate `GBM higgs 50 trees` whose accepted
wall-clock band is 72-77 s (BASELINE.md, `compareBenchmarksStage.groovy:45-49`).
The dataset is synthesized HIGGS-shaped data (the real HIGGS file is not in the
image; rows x cols x dtype match, which is what the histogram engine's cost
depends on). vs_baseline = our_seconds / baseline_midpoint — < 1.0 means faster
than the reference band.

Env overrides: H2O_TPU_BENCH_ROWS, H2O_TPU_BENCH_TREES (for quick smoke runs).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_S = 74.5  # midpoint of the reference's 72-77 s accepted band


def main():
    nrow = int(os.environ.get("H2O_TPU_BENCH_ROWS", 11_000_000))
    ntrees = int(os.environ.get("H2O_TPU_BENCH_TREES", 50))
    ncol = 28

    import jax
    import h2o_tpu as h2o
    from h2o_tpu.frame.frame import Frame
    from h2o_tpu.frame.vec import T_CAT, Vec
    from h2o_tpu.models.gbm import GBM, GBMParameters

    rng = np.random.default_rng(42)
    # HIGGS: 28 continuous physics features, binary response.
    cols = {}
    latent = rng.normal(size=nrow).astype(np.float32)
    for j in range(ncol):
        mix = 0.3 if j % 3 == 0 else 0.0
        cols[f"f{j}"] = (rng.normal(size=nrow).astype(np.float32)
                         + mix * latent).astype(np.float32)
    logits = latent + 0.5 * cols["f0"] - 0.25 * cols["f3"]
    y = (rng.random(nrow) < 1 / (1 + np.exp(-logits))).astype(np.int32)

    fr = Frame.from_dict(cols)
    fr.add("response", Vec.from_numpy(y.astype(np.float32), type=T_CAT,
                                      domain=["b", "s"]))

    # Chunked scan: the train program compiles per chunk length, so warm-up
    # and the timed run MUST share score_tree_interval — otherwise the timed
    # run recompiles (a 20-40s artifact that the reference's warm JVM never
    # pays in its CI bands). Default: ONE chunk (score once, at the end) —
    # each chunk dispatch re-ships the 1.2 GB binned matrix through the
    # device tunnel (~6 s/chunk here); the reference's default scoring is
    # time-gated and also scores only a handful of times over a 1-min run.
    interval = max(1, min(int(os.environ.get("H2O_TPU_BENCH_INTERVAL", ntrees)),
                          ntrees))
    while ntrees % interval:  # warm-up compiles ONE chunk length; make the
        interval -= 1         # chunks uniform so no remainder-chunk recompile
    params = GBMParameters(training_frame=fr, response_column="response",
                           ntrees=ntrees, max_depth=5, nbins=20,
                           learn_rate=0.1, seed=42,
                           score_tree_interval=interval)
    warm = params.clone(ntrees=interval)
    GBM(warm).train_model()

    t0 = time.time()
    model = GBM(params).train_model()
    dt = time.time() - t0

    auc = model.output.training_metrics.auc
    print(json.dumps({
        "metric": "gbm_higgs11m_50trees_train_wall",
        "value": round(dt, 3),
        "unit": "s",
        "vs_baseline": round(dt / BASELINE_S, 4),
        "detail": {"rows": nrow, "cols": ncol, "ntrees": ntrees,
                   "train_auc": None if auc is None else round(float(auc), 4),
                   "baseline_band_s": [72, 77],
                   "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
