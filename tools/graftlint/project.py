"""graftlint pass 1 — the repo-wide project model the interprocedural
rules (tools/graftlint/concurrency.py) analyze.

Per-file extraction produces a plain-dict **FileSummary** (JSON-able, so
the incremental cache under ``.graftlint_cache/`` can persist it keyed on
content hash): every function/method with the ``self.*`` fields it reads
and writes, the guards (locks) held at each access, the calls it makes,
the locks it acquires, the threads it spawns and joins. A **ProjectModel**
assembles all summaries into:

- a symbol table (module functions, class methods, per-class lock attrs);
- an approximate **call graph** — ``self.m()`` resolves within the class,
  bare/imported names resolve through the per-file import map, and
  ``obj.m()`` resolves through a *unique-method-name* index (if exactly
  one class in the project defines ``m`` and the name is not on the
  common-name blocklist, the edge is taken — deliberately
  under-approximate: an unresolved call produces no edge, never a wrong
  one... except where a non-unique spelling collides, which the blocklist
  exists to prevent);
- a **thread-entry map**: every ``threading.Thread(target=...)`` (and
  ``Timer``), every callable handed to a ``.start(fn)``-shaped job/worker
  dispatch, every ``do_*`` method of a ``BaseHTTPRequestHandler``
  subclass (REST handler threads — ThreadingHTTPServer runs each request
  on its own thread), and every callable registered through an
  ``add_*hook``/``register_*hook`` call (Cleaner sweep hooks) is a thread
  root; the transitive closure over the call graph is the code that runs
  on a non-main thread.

Guard tracking: ``with self._lock:`` / ``with _MODULE_LOCK:`` scopes push
a lock token for their body; a bare ``x.acquire(...)`` holds its token
for the remainder of the enclosing block (the try/finally idiom). Tokens:

- ``self.<attr>``   — instance lock (normalized per-class in the model)
- ``mod:<NAME>``    — module-level lock of the same file
- ``ext:<attr>``    — a lock attribute on some OTHER object (``vec._lock``
  in the Cleaner) — resolved per-class only when the attr names a lock in
  exactly one class, else kept out of the cycle graph (ambiguous nodes
  would merge distinct locks and fabricate cycles)

Nested functions/lambdas are extracted as their OWN functions (their
bodies run when called, not where defined — guards at the definition site
do not apply), inheriting the enclosing class context so a worker closure
that captures ``self`` still attributes its field accesses to the class
(the `Job.start._run` shape).

Pass 3 (``tools/graftlint/dataflow.py``) consumes an additional per-
function **provenance event stream** extracted here: where values acquire
a device placement (``mesh.put_*``, ``BinnedView.build``, ``jnp.*``) or a
host domain (``np.*``, ``device_get``), which host-transfer ops touch
them, which calls carry them (with positional argument refs, so donation
and placement can be traced ACROSS functions), and which jitted callables
are constructed/called where. The events are deliberately shallow —
plain-name and ``self.attr`` refs only, last-bind-wins — so the dataflow
rules stay under-approximate the same way the call graph is: a missing
tag produces no finding, never a wrong one.

Stdlib ``ast`` only — the linter never imports the package it lints.
"""

from __future__ import annotations

import ast
import os

from .core import collect_aliases, normalize, dotted_name, traced_scopes

#: bump when the summary shape changes — the incremental cache keys on it
#: (4: the pass-3 provenance event stream / params / traced flags)
SUMMARY_FORMAT = 4

#: constructors whose result is a lock-like guard (Condition guards too:
#: `with self._cv:` owns the underlying lock)
_LOCK_CTOR_SUFFIXES = ("threading.Lock", "threading.RLock",
                       "threading.Condition", "sanitizer.make_lock",
                       "make_lock")
#: constructors of non-lock sync primitives — exempt from field analysis
#: (an Event is its own synchronization, not shared data)
_SYNC_CTOR_SUFFIXES = _LOCK_CTOR_SUFFIXES + (
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "contextvars.ContextVar")

#: attr spellings treated as locks even without a visible declaration
#: (helper classes whose __init__ lives in another file)
_LOCKISH_ATTRS = ("lock", "mutex", "_cv", "cv")

#: method names too common to resolve through the unique-name index — a
#: wrong edge is worse than a missing one
_RESOLVE_BLOCKLIST = {
    "get", "put", "set", "add", "pop", "append", "extend", "remove",
    "clear", "copy", "update", "items", "keys", "values", "join", "split",
    "strip", "encode", "decode", "format", "index", "count", "insert",
    "sort", "read", "write", "close", "open", "flush", "seek", "tell",
    "start", "stop", "run", "send", "recv", "acquire", "release", "wait",
    "notify", "notify_all", "is_set", "mkdir", "exists", "search",
    "match", "group", "lower", "upper", "replace", "startswith",
    "endswith", "info", "keys", "name", "next", "reset", "submit",
}


def _lockish(attr: str) -> bool:
    a = attr.lower()
    return any(t in a for t in _LOCKISH_ATTRS)


def _is_lock_ctor(node: ast.AST, aliases: dict) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = normalize(dotted_name(node.func), aliases)
    return bool(fn) and fn.endswith(_LOCK_CTOR_SUFFIXES)


def _is_sync_ctor(node: ast.AST, aliases: dict) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = normalize(dotted_name(node.func), aliases)
    return bool(fn) and fn.endswith(_SYNC_CTOR_SUFFIXES)


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self.x` attribute node, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _FnState:
    """Mutable record of one function's summary while extracting."""

    def __init__(self, qual: str, cls: str | None, name: str, line: int):
        self.qual = qual
        self.cls = cls
        self.name = name
        self.line = line
        self.reads: list = []       # [field, [guards], line]
        self.writes: list = []      # [field, [guards], line]
        self.calls: list = []       # [kind, name, recv, [guards], line]
        self.acquires: list = []    # [token, [held], line]
        self.spawns: list = []      # [target_ref, store_attr, line]
        self.joins: list = []       # tokens joined ("self._worker", "L")
        self.root_hints: list = []  # ["rest-handler"]
        self.locals_alias: dict[str, str] = {}   # local -> "self.attr"
        self.local_threads: set[str] = set()     # locals holding a Thread

    def summary(self) -> dict:
        return {"qual": self.qual, "cls": self.cls, "name": self.name,
                "public": not self.name.startswith("_"),
                "line": self.line, "reads": self.reads,
                "writes": self.writes, "calls": self.calls,
                "acquires": self.acquires, "spawns": self.spawns,
                "joins": sorted(set(self.joins)),
                "root_hints": self.root_hints}


# ---------------------------------------------------------------------------
# pass-3 provenance extraction (consumed by tools/graftlint/dataflow.py)
# ---------------------------------------------------------------------------
#: attribute spellings the frame layer uses for device-resident payloads —
#: `arr = vec._data` / `codes = view.codes` taints the local as device
_DEVICE_ATTRS = {"data", "_data", "codes"}

#: host-cast builtins (flagged only on device-tagged operands)
_HOST_CASTS = {"float", "int", "bool"}


def _ref_of(node) -> str | None:
    """'x' for a Name, 'self.x' for a self-attribute, else None."""
    if isinstance(node, ast.Name):
        return node.id
    attr = _self_attr(node)
    if attr is not None:
        return f"self.{attr}"
    return None


def _static_valued(node) -> bool:
    """Trace/host-static expressions: literals or anything derived from
    .shape/.ndim/len() — python values, never a device sync."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype", "itemsize", "nbytes"):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
    return False


def _int_positions(value) -> list:
    """Sorted int literals out of an int / tuple-of-ints AST value."""
    if isinstance(value, ast.Constant) and isinstance(value.value, int):
        return [value.value]
    if isinstance(value, (ast.Tuple, ast.List)):
        return sorted(e.value for e in value.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, int))
    return []


class _ProvVisitor:
    """One function body → the provenance event stream. Walks statements
    in order WITHOUT entering nested function scopes (each nested scope
    gets its own stream); maintains the active-loop stack so per-iteration
    bindings are distinguishable from loop-invariant ones."""

    def __init__(self, aliases: dict, traced: bool):
        self.aliases = aliases
        self.traced = traced
        self.events: list = []
        self._loops: list[set] = []      # stack of loop-assigned name sets
        self._uses: list = []            # raw Name loads, filtered at end
        self._kills: list = []           # raw stores, filtered at end
        self._interesting: set = set()   # dcall args + pack elts

    # -- source classification -------------------------------------------------
    def _norm(self, node) -> str | None:
        return normalize(dotted_name(node), self.aliases)

    def _src_tag(self, value) -> str | None:
        """Provenance tag of a bound expression: 'row'/'rep'/'dev'/'host',
        or None when unknown (unknown never produces a finding)."""
        if isinstance(value, ast.Attribute) and value.attr in _DEVICE_ATTRS:
            return "dev"
        if not isinstance(value, ast.Call):
            return None
        fn = self._norm(value.func) or ""
        tail = fn.rsplit(".", 1)[-1]
        if tail == "put_row_sharded" or fn.endswith("BinnedView.build"):
            return "row"
        if tail == "put_replicated":
            return "rep"
        if fn == "jax.device_put":
            # refine by the sharding argument when it names a mesh helper
            target = value.args[1] if len(value.args) >= 2 else None
            for kw in value.keywords:
                if kw.arg in ("device", "sharding"):
                    target = kw.value
            if isinstance(target, ast.Call):
                t = (self._norm(target.func) or "").rsplit(".", 1)[-1]
                if t == "row_sharding":
                    return "row"
                if t == "replicated":
                    return "rep"
            return "dev"
        if (fn.startswith(("jnp.", "lax."))
                or tail in ("put_sharded", "mr_map", "mr_reduce")):
            return "dev"
        if (fn.startswith("np.") or fn == "jax.device_get"
                or tail in ("to_numpy", "tolist")):
            return "host"
        return None

    def _callee(self, func) -> tuple | None:
        """(kind, name) for a call's callee — kinds match
        ProjectModel.resolve_call; bare names imported from another module
        resolve through the alias map into 'dotted' form."""
        if isinstance(func, ast.Name):
            full = self.aliases.get(func.id)
            if full and "." in full:
                return ("dotted", full)
            return ("name", func.id)
        a = _self_attr(func)
        if a is not None:
            return ("self", a)
        dn = self._norm(func)
        if dn and "." in dn:
            return ("dotted", dn)
        if isinstance(func, ast.Attribute):
            return ("attr", func.attr)
        return None

    def _loopvar(self, node) -> bool:
        """Does the expression read any name assigned inside an enclosing
        loop (i.e. vary per iteration)?"""
        if not self._loops:
            return False
        live = set().union(*self._loops)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in live:
                return True
        return False

    def _span(self, node) -> tuple:
        return (node.lineno, node.col_offset,
                getattr(node, "end_lineno", node.lineno) or node.lineno,
                getattr(node, "end_col_offset", 0) or 0)

    # -- binding classification ------------------------------------------------
    def _donate_positions(self, value) -> list:
        """Donated positions of a LITERAL donating jit bind (IfExp arms
        unioned — donation assumed when any arm donates, rule 18's
        convention)."""
        if isinstance(value, ast.IfExp):
            return sorted(set(self._donate_positions(value.body))
                          | set(self._donate_positions(value.orelse)))
        if not isinstance(value, ast.Call):
            return []
        fn = self._norm(value.func) or ""
        if not (fn.endswith("jax.jit") or fn == "jit"):
            return []
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                return _int_positions(kw.value)
        return []

    def _first_call(self, value):
        if isinstance(value, ast.IfExp):
            return self._first_call(value.body) or \
                self._first_call(value.orelse)
        return value if isinstance(value, ast.Call) else None

    def _bind(self, target: str, value, line: int) -> None:
        tag = self._src_tag(value)
        if tag is not None:
            self.events.append(["src", target, tag, line])
            return
        don = self._donate_positions(value)
        if don:
            self.events.append(["don", target, don, line])
        call = self._first_call(value)
        if isinstance(call, ast.Call):
            fn = self._norm(call.func) or ""
            if fn.endswith("jax.jit") or fn == "jit":
                static: list = []
                for kw in call.keywords:
                    # merge across both spellings — static_argnames yields
                    # no int positions, and must not ERASE static_argnums'
                    if kw.arg in ("static_argnums", "static_argnames"):
                        static += _int_positions(kw.value)
                self.events.append(["jit", target, sorted(set(static)),
                                    line])
                return
            callee = self._callee(call.func)
            if callee is not None:
                argrefs = [(_ref_of(a) if not isinstance(a, ast.Starred)
                            else None) for a in call.args]
                self.events.append(["bindcall", target, callee[0],
                                    callee[1], argrefs, line])
            return
        if isinstance(value, (ast.Tuple, ast.List)):
            elts = [_ref_of(e) for e in value.elts]
            self.events.append(["pack", target, elts, line])
            self._interesting.update(e for e in elts if e)

    # -- statement walk --------------------------------------------------------
    def walk(self, stmts: list) -> list:
        for s in stmts:
            self._stmt(s)
        # finalize: filter use/kill streams to the names the donation
        # analysis can actually reason about (dcall args + pack elements)
        keep = self._interesting
        for name, line, col, ecol in self._uses:
            if name in keep:
                self.events.append(["use", name, line, col, ecol])
        for name, endline in self._kills:
            if name in keep:
                self.events.append(["kill", name, endline])
        return self.events

    def _stmt(self, s) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # nested scopes extracted on their own
        # stores clear donated state at the STATEMENT's end (RHS evaluates
        # before targets bind — `f, o = step(x, f)` is the clean idiom).
        # Synthesized wrappers (a lambda body re-boxed as an Expr) carry
        # no position of their own
        self._stmt_end = (getattr(s, "end_lineno", None)
                          or getattr(s, "lineno", 0) or 0)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            assigned = {n.id for n in ast.walk(s.target)
                        if isinstance(n, ast.Name)}
            for sub in ast.walk(s):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, (ast.Store, ast.Del)):
                    assigned.add(sub.id)
            self._scan_expr(s.iter)
            self._loops.append(assigned)
            for b in s.body + s.orelse:
                self._stmt(b)
            self._loops.pop()
            for n in ast.walk(s.target):
                if isinstance(n, ast.Name):
                    self._kills.append((n.id, s.lineno))
            return
        if isinstance(s, ast.While):
            assigned = {n.id for n in ast.walk(s)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, (ast.Store, ast.Del))}
            self._truth(s.test)
            self._scan_expr(s.test)
            self._loops.append(assigned)
            for b in s.body + s.orelse:
                self._stmt(b)
            self._loops.pop()
            return
        if isinstance(s, ast.If):
            self._truth(s.test)
            self._scan_expr(s.test)
            for b in s.body + s.orelse:
                self._stmt(b)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._scan_expr(item.context_expr)
            for b in s.body:
                self._stmt(b)
            return
        if isinstance(s, ast.Try):
            for b in s.body + s.orelse + s.finalbody:
                self._stmt(b)
            for h in s.handlers:
                for b in h.body:
                    self._stmt(b)
            return
        if isinstance(s, ast.Return):
            if s.value is not None:
                self._scan_expr(s.value)
                ref = _ref_of(s.value)
                if ref is not None:
                    self.events.append(["ret", ref, s.lineno])
                elif isinstance(s.value, (ast.Tuple, ast.List)):
                    self.events.append(
                        ["retpack", [_ref_of(e) for e in s.value.elts],
                         s.lineno])
                elif isinstance(s.value, ast.Call):
                    tag = self._src_tag(s.value)
                    if tag is not None:
                        self.events.append(["rettag", tag, s.lineno])
                    else:
                        callee = self._callee(s.value.func)
                        if callee is not None:
                            self.events.append(
                                ["retcall", callee[0], callee[1],
                                 s.lineno])
            return
        if isinstance(s, ast.Assign):
            # rebinds drop stale provenance tags FIRST (phase order in the
            # pass-3 env walk: flag < unbind < bind at the same line) — a
            # stale tag could otherwise fabricate a finding. Anchored at
            # the statement's FIRST line, same as the bind: on a wrapped
            # `v = mesh.put_*(\n x)` an end-line unbind would sort after
            # the bind and erase the tag the statement just established
            for t in s.targets:
                for n in ast.walk(t):
                    ref = _ref_of(n)
                    if ref is not None and not isinstance(
                            getattr(n, "ctx", None), ast.Load):
                        self.events.append(["unbind", ref, s.lineno])
            if len(s.targets) == 1:
                tgt = _ref_of(s.targets[0])
                if tgt is not None:
                    self._bind(tgt, s.value, s.lineno)
        if isinstance(s, ast.AugAssign) and isinstance(s.op, ast.Add) \
                and isinstance(s.target, ast.Name) \
                and isinstance(s.value, (ast.Tuple, ast.List)):
            # `args += (x,)` — tuple append preserves existing positions
            self.events.append(["packext", s.target.id,
                                [_ref_of(e) for e in s.value.elts],
                                s.lineno])
            self._interesting.update(_ref_of(e) for e in s.value.elts
                                     if _ref_of(e))
        self._scan_expr(s)

    def _truth(self, test) -> None:
        """Implicit-bool reads: `if x:` / `while x:` / `if not x:` /
        BoolOp operands that are bare refs."""
        nodes = [test]
        if isinstance(test, ast.BoolOp):
            nodes = list(test.values)
        elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            nodes = [test.operand]
        for n in nodes:
            ref = _ref_of(n)
            if ref is not None:
                ln, col, _eln, ecol = self._span(n)
                self.events.append(["truth", ref, ln, col, ecol])

    def _scan_expr(self, root) -> None:
        """Event extraction from one statement's expressions, skipping
        nested function scopes."""
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    ln, col, eln, ecol = self._span(node)
                    self._uses.append((node.id, ln, col, ecol))
                else:
                    self._kills.append(
                        (node.id,
                         getattr(self, "_stmt_end", None)
                         or getattr(node, "end_lineno", node.lineno)
                         or node.lineno))
            elif isinstance(node, ast.BinOp) and not self.traced:
                lref, rref = _ref_of(node.left), _ref_of(node.right)
                if lref and rref:
                    ln, col, eln, ecol = self._span(node)
                    self.events.append(
                        ["combine", lref, rref, ln, col, ecol])
            elif isinstance(node, ast.Call):
                self._call(node)
            stack.extend(ast.iter_child_nodes(node))

    def _argdesc(self, a) -> list:
        if isinstance(a, ast.Starred):
            return ["star", _ref_of(a.value), False]
        ref = _ref_of(a)
        if ref is not None:
            return ["name", ref, self._loopvar(a)]
        if isinstance(a, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            return ["comp", None, self._loopvar(a)]
        if isinstance(a, ast.List):
            return ["list", None, self._loopvar(a)]
        if isinstance(a, ast.Dict):
            return ["dict", None, self._loopvar(a)]
        if isinstance(a, ast.Set):
            return ["set", None, self._loopvar(a)]
        if isinstance(a, ast.Constant):
            return ["const", None, False]
        return ["other", None, self._loopvar(a)]

    def _call(self, node: ast.Call) -> None:
        fn = self._norm(node.func) or ""
        ln, col, eln, ecol = self._span(node)
        # compiled-callable construction inside a loop (rule 22): a fresh
        # jit / tracked wrapper / AOT lower per iteration compiles every
        # time (the jit cache is keyed on the callable's identity)
        if self._loops:
            is_jit_ctor = (fn.endswith("jax.jit") or fn == "jit"
                           or fn.endswith("programs.tracked"))
            is_lower = (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "lower" and bool(node.args))
            if is_jit_ctor or is_lower:
                what = "jit" if is_jit_ctor else "lower"
                self.events.append(["jitloop", what, ln, col, ecol])
        # host-transfer ops (rule 20) — explicit jax.device_get is the
        # sanctioned spelling and deliberately NOT recorded here
        ref = None
        op = None
        if isinstance(node.func, ast.Name) \
                and node.func.id in _HOST_CASTS and node.args \
                and not _static_valued(node.args[0]):
            ref = _ref_of(node.args[0])
            op = f"{node.func.id}()"
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in ("item", "tolist")):
            ref = _ref_of(node.func.value)
            op = f".{node.func.attr}()"
        elif fn.startswith("np.") and node.args:
            ref = _ref_of(node.args[0])
            op = fn
        if ref is not None and op is not None:
            self.events.append(["host", op, ref, ln, col, ecol])
        # calls with traceable positional refs (rules 22/23): the callee
        # IfExp form `(a if c else b)(*args)` records one dcall per arm
        callees = []
        if isinstance(node.func, ast.IfExp):
            for arm in (node.func.body, node.func.orelse):
                if isinstance(arm, ast.Name):
                    callees.append(("name", arm.id))
        else:
            c = self._callee(node.func)
            if c is not None:
                callees.append(c)
        if not callees or not node.args:
            return
        descs = [self._argdesc(a) for a in node.args]
        if not any(d[0] in ("name", "star", "list", "dict", "set", "comp")
                   for d in descs):
            return
        for kind, name in callees:
            self.events.append(["dcall", kind, name, descs, ln, col, eln,
                                ecol])
        for d in descs:
            if d[0] in ("name", "star") and d[1]:
                self._interesting.add(d[1])


def _extract_prov(body: list, aliases: dict, traced: bool) -> list:
    return _ProvVisitor(aliases, traced).walk(body)


class _Extractor:
    """Per-file AST walk → FileSummary dict."""

    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        self.aliases = collect_aliases(tree)
        self.module_locks: set[str] = set()
        self.functions: dict[str, dict] = {}
        self.classes: dict[str, dict] = {}
        #: function/lambda nodes under a jax trace — pass-3 skips combine
        #: events in them (in-shard_map mixing is the sanctioned shape)
        self.traced_nodes = traced_scopes(tree, self.aliases)
        self._collect_module_locks()

    @staticmethod
    def _params_of(node) -> list:
        args = getattr(node, "args", None)
        if args is None:
            return []
        return [a.arg for a in getattr(args, "posonlyargs", []) + args.args]

    def _collect_module_locks(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value,
                                                              self.aliases):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)

    # -- class prep -----------------------------------------------------------
    def _class_lock_attrs(self, cls: ast.ClassDef) -> tuple[set, set]:
        """(lock attrs, all sync attrs) declared anywhere in the class via
        `self.x = threading.Lock()/.../sanitizer.make_lock(...)`."""
        locks: set[str] = set()
        syncs: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if _is_lock_ctor(node.value, self.aliases):
                    locks.add(attr)
                if _is_sync_ctor(node.value, self.aliases):
                    syncs.add(attr)
        return locks, syncs

    # -- extraction -----------------------------------------------------------
    def extract(self) -> dict:
        # module body as a pseudo-function (module-level spawns/locks);
        # top-level defs are extracted by _walk_top below, not here
        mod_stmts = [s for s in self.tree.body
                     if not isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))]
        self._extract_scope(mod_stmts, "<module>", None, "<module>", 1,
                            class_locks=set(), class_syncs=set())
        for node in self.tree.body:
            self._walk_top(node, prefix="")
        return {
            "path": self.relpath,
            "format": SUMMARY_FORMAT,
            "module_locks": sorted(self.module_locks),
            "functions": self.functions,
            "classes": self.classes,
        }

    def _walk_top(self, node: ast.AST, prefix: str,
                  cls_ctx: str | None = None,
                  class_locks: set | None = None,
                  class_syncs: set | None = None) -> None:
        if isinstance(node, ast.ClassDef):
            locks, syncs = self._class_lock_attrs(node)
            bases = [dotted_name(b) or "" for b in node.bases]
            qual = f"{prefix}{node.name}"
            self.classes[node.name] = {
                "qual": qual, "locks": sorted(locks),
                "bases": bases, "methods": [], "line": node.lineno,
            }
            handler = any(b.split(".")[-1] == "BaseHTTPRequestHandler"
                          for b in bases)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    st = self._extract_scope(
                        sub.body, f"{qual}.{sub.name}", node.name, sub.name,
                        sub.lineno, class_locks=locks, class_syncs=syncs,
                        params=self._params_of(sub),
                        traced=sub in self.traced_nodes)
                    if handler and sub.name.startswith("do_"):
                        st.root_hints.append("rest-handler")
                    self.classes[node.name]["methods"].append(sub.name)
                else:
                    self._walk_top(sub, prefix=f"{qual}.")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._extract_scope(node.body, f"{prefix}{node.name}", cls_ctx,
                                node.name, node.lineno,
                                class_locks=class_locks or set(),
                                class_syncs=class_syncs or set(),
                                params=self._params_of(node),
                                traced=node in self.traced_nodes)
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for sub in ast.iter_child_nodes(node):
                self._walk_top(sub, prefix, cls_ctx, class_locks,
                               class_syncs)

    # -- one function body ----------------------------------------------------
    def _extract_scope(self, body: list, qual: str, cls: str | None,
                       name: str, line: int, *, class_locks: set,
                       class_syncs: set, params=(),
                       traced: bool = False) -> _FnState:
        st = _FnState(qual, cls, name, line)
        self._nested: list[tuple] = []
        self._walk_block(body, (), st, class_locks, class_syncs)
        summary = st.summary()
        summary["params"] = list(params)
        summary["traced"] = bool(traced)
        summary["prov"] = _extract_prov(body, self.aliases, traced)
        self.functions[qual] = summary
        # nested defs extracted AFTER the parent (guards do not inherit:
        # a closure body runs when called, not where defined)
        for sub, subqual in self._pop_nested():
            sub_body = (sub.body if isinstance(sub, (ast.FunctionDef,
                                                     ast.AsyncFunctionDef))
                        else [ast.Expr(value=sub.body)])
            self._extract_scope(sub_body, subqual, cls,
                                subqual.rsplit(".", 1)[-1],
                                getattr(sub, "lineno", line),
                                class_locks=class_locks,
                                class_syncs=class_syncs,
                                params=self._params_of(sub),
                                traced=sub in self.traced_nodes)
        return st

    def _pop_nested(self):
        out, self._nested = self._nested, []
        return out

    def _lock_token(self, expr: ast.AST, st: _FnState,
                    class_locks: set) -> str | None:
        """Lock token for a with-item / acquire receiver, or None when the
        expression is not lock-like."""
        attr = _self_attr(expr)
        if attr is not None:
            if attr in class_locks or _lockish(attr):
                return f"self.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks or _lockish(expr.id):
                return f"mod:{expr.id}"
            return None
        if isinstance(expr, ast.Attribute) and _lockish(expr.attr):
            return f"ext:{expr.attr}"
        return None

    def _walk_block(self, stmts: list, guards: tuple, st: _FnState,
                    class_locks: set, class_syncs: set) -> None:
        guards = tuple(guards)
        for stmt in stmts:
            guards = self._walk_stmt(stmt, guards, st, class_locks,
                                     class_syncs)

    def _walk_stmt(self, stmt: ast.AST, guards: tuple, st: _FnState,
                   class_locks: set, class_syncs: set) -> tuple:
        """Process one statement; returns the guard set for the NEXT
        statement in the block (a bare `.acquire()` extends it)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested.append((stmt, f"{st.qual}.{stmt.name}"))
            return guards
        if isinstance(stmt, ast.ClassDef):
            self._walk_top(stmt, prefix=f"{st.qual}.")
            return guards
        if isinstance(stmt, ast.With):
            inner = list(guards)
            for item in stmt.items:
                tok = self._lock_token(item.context_expr, st, class_locks)
                if tok is not None:
                    st.acquires.append([tok, list(inner), stmt.lineno,
                                        True])
                    inner.append(tok)
                self._scan_expr(item.context_expr, guards, st, class_locks,
                                class_syncs)
            self._walk_block(stmt.body, tuple(inner), st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, ast.Try):
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            for h in stmt.handlers:
                self._walk_block(h.body, guards, st, class_locks,
                                 class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            self._walk_block(stmt.finalbody, guards, st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            # `for t in threads:` over a local thread list — joins on the
            # loop variable drain the whole list
            if (isinstance(stmt.iter, ast.Name)
                    and stmt.iter.id in st.local_threads
                    and isinstance(stmt.target, ast.Name)):
                st.locals_alias[stmt.target.id] = f"localiter:{stmt.iter.id}"
            self._scan_expr(stmt.iter, guards, st, class_locks, class_syncs)
            self._scan_expr(stmt.target, guards, st, class_locks,
                            class_syncs)
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            return guards
        # simple statement: scan expressions, track aliases/acquire
        new_guards = self._scan_simple(stmt, guards, st, class_locks,
                                       class_syncs)
        return new_guards

    def _scan_simple(self, stmt: ast.AST, guards: tuple, st: _FnState,
                     class_locks: set, class_syncs: set) -> tuple:
        # local alias tracking: `w = self._shadow_worker`
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = stmt.targets[0].id
            src_attr = _self_attr(stmt.value)
            if src_attr is not None:
                st.locals_alias[tgt] = f"self.{src_attr}"
        self._scan_expr(stmt, guards, st, class_locks, class_syncs)
        # a bare `<lock>.acquire(...)` holds for the rest of the block;
        # `.release()` drops it (the try/finally idiom — approximate)
        out = list(guards)
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            tok = self._lock_token(node.func.value, st, class_locks)
            if tok is None:
                continue
            if node.func.attr == "acquire":
                blocking = True
                for kw in node.keywords:
                    if (kw.arg == "blocking"
                            and isinstance(kw.value, ast.Constant)):
                        blocking = bool(kw.value.value)
                if node.args and isinstance(node.args[0], ast.Constant):
                    blocking = bool(node.args[0].value)
                # non-blocking acquires still HOLD on success — they are
                # an edge source but never an inversion victim; keep them
                # as held guards, the cycle rule cares about order only
                st.acquires.append([tok, list(out), node.lineno,
                                    blocking])
                if tok not in out:
                    out.append(tok)
            elif node.func.attr == "release" and tok in out:
                out.remove(tok)
        return tuple(out)

    def _scan_expr(self, root: ast.AST, guards: tuple, st: _FnState,
                   class_locks: set, class_syncs: set) -> None:
        """Collect field accesses / calls / spawns from an expression tree
        without descending into nested function scopes."""
        stack = [(root, "load")]
        while stack:
            node, mode = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._nested.append((node, f"{st.qual}.{node.name}"))
                continue
            if isinstance(node, ast.Lambda):
                self._nested.append(
                    (node, f"{st.qual}.<lambda:{node.lineno}>"))
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    stack.append((t, "store"))
                stack.append((node.value, "load"))
                self._check_spawn_store(node, st, guards)
                continue
            if isinstance(node, ast.AugAssign):
                stack.append((node.target, "both"))
                stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.AnnAssign):
                if node.target is not None:
                    stack.append((node.target, "store"))
                if node.value is not None:
                    stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None and attr not in class_syncs \
                        and not (attr in class_locks or _lockish(attr)):
                    g = list(guards)
                    if mode in ("store", "both"):
                        st.writes.append([attr, g, node.lineno])
                    if mode in ("load", "both"):
                        st.reads.append([attr, g, node.lineno])
                stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.Call):
                self._record_call(node, guards, st, class_locks)
                for sub in ast.iter_child_nodes(node):
                    stack.append((sub, "load"))
                continue
            for sub in ast.iter_child_nodes(node):
                stack.append((sub, mode if isinstance(node, (ast.Tuple,
                                                             ast.List))
                              else "load"))

    # -- call / spawn recording ----------------------------------------------
    def _callable_ref(self, node: ast.AST, st: _FnState) -> str | None:
        """Reference string for a callable expression (thread target /
        dispatched worker fn)."""
        attr = _self_attr(node)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(node, ast.Name):
            return f"name:{node.id}"
        if isinstance(node, ast.Lambda):
            self._nested.append((node, f"{st.qual}.<lambda:{node.lineno}>"))
            return f"local:{st.qual}.<lambda:{node.lineno}>"
        dn = dotted_name(node)
        if dn:
            return f"dotted:{dn}"
        return None

    def _check_spawn_store(self, assign: ast.Assign, st: _FnState,
                           guards: tuple) -> None:
        """`self.X = threading.Thread(...)` / `t = threading.Thread(...)`
        / `threads = [threading.Thread(...) for ...]` — record the storage
        so joins (incl. `for t in threads: t.join()`) can be matched."""
        call = assign.value
        if isinstance(call, (ast.ListComp, ast.GeneratorExp)):
            inner = next((n for n in ast.walk(call.elt)
                          if isinstance(n, ast.Call)
                          and (normalize(dotted_name(n.func), self.aliases)
                               or "").endswith(("threading.Thread",
                                                "threading.Timer"))), None)
            if inner is not None:
                for t in assign.targets:
                    if isinstance(t, ast.Name):
                        st.local_threads.add(t.id)
                        self._note_spawn(inner, st, store=f"local:{t.id}")
                        return
            return
        if not isinstance(call, ast.Call):
            return
        fn = normalize(dotted_name(call.func), self.aliases)
        if not fn or not fn.endswith(("threading.Thread",
                                      "threading.Timer")):
            return
        for t in assign.targets:
            attr = _self_attr(t)
            if attr is not None:
                self._note_spawn(call, st, store=f"self.{attr}")
                return
            if isinstance(t, ast.Name):
                st.local_threads.add(t.id)
                self._note_spawn(call, st, store=f"local:{t.id}")
                return
        self._note_spawn(call, st, store=None)

    def _note_spawn(self, call: ast.Call, st: _FnState,
                    store: str | None) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = self._callable_ref(kw.value, st)
        if target is None and call.args:
            target = self._callable_ref(call.args[0], st)
        # dedupe: _record_call sees the same Call node again
        for sp in st.spawns:
            if sp[2] == call.lineno:
                return
        st.spawns.append([target, store, call.lineno, "thread"])

    def _record_call(self, node: ast.Call, guards: tuple,
                     st: _FnState, class_locks: set) -> None:
        fn = normalize(dotted_name(node.func), self.aliases)
        line = node.lineno
        g = list(guards)
        # thread spawn (anonymous / unstored form)
        if fn and fn.endswith(("threading.Thread", "threading.Timer")):
            self._note_spawn(node, st, store=None)
            return
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            recv = None
            a = _self_attr(node.func.value)
            if a is not None:
                recv = f"self.{a}"
            elif isinstance(node.func.value, ast.Name):
                nm = node.func.value.id
                recv = st.locals_alias.get(nm, f"name:{nm}")
            elif isinstance(node.func.value, ast.Constant):
                recv = "literal"
            # join bookkeeping for unjoined-thread
            if meth == "join" and recv and recv != "literal":
                if recv.startswith("self."):
                    st.joins.append(recv)
                elif (recv.startswith("name:")
                        and recv[5:] in st.local_threads):
                    st.joins.append(f"local:{recv[5:]}")
                elif recv.startswith("localiter:"):
                    st.joins.append(f"local:{recv[10:]}")
            # `.start(fn)` with a callable argument = a worker dispatch
            # (Thread.start takes no args, so this is Job.start-shaped)
            if meth == "start" and node.args:
                ref = self._callable_ref(node.args[0], st)
                if ref is not None:
                    st.spawns.append([ref, None, line, "dispatch"])
            # hook registration: the callable runs on someone else's thread
            if (("hook" in meth and meth.startswith(("add_", "register_")))
                    and node.args):
                ref = self._callable_ref(node.args[0], st)
                if ref is not None:
                    st.spawns.append([ref, None, line, "dispatch"])
            if self._self_call(node, st):
                st.calls.append(["self", meth, None, g, line])
            elif fn is not None:
                st.calls.append(["dotted", fn, recv, g, line])
            else:
                st.calls.append(["attr", meth, recv, g, line])
        elif isinstance(node.func, ast.Name):
            st.calls.append(["name", node.func.id, None, g, line])
        elif fn is not None:
            st.calls.append(["dotted", fn, None, g, line])

    @staticmethod
    def _self_call(node: ast.Call, st: _FnState) -> bool:
        return (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self")


def extract_summary(relpath: str, source: str) -> dict | None:
    """FileSummary for one source file (None on syntax errors — the
    per-file rules report those)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    return _Extractor(relpath.replace(os.sep, "/"), tree).extract()


# ---------------------------------------------------------------------------
# the assembled model
# ---------------------------------------------------------------------------
class ProjectModel:
    """All file summaries resolved into one queryable graph."""

    def __init__(self, summaries: dict[str, dict]):
        #: path -> summary (insertion order = scan order; keep sorted)
        self.files = {p: s for p, s in sorted(summaries.items())
                      if s is not None}
        #: fnkey ("path::qual") -> function summary (+ "path")
        self.functions: dict[str, dict] = {}
        #: (path, class) -> class record
        self.classes: dict[tuple, dict] = {}
        #: method name -> [fnkey] across all classes (unique-name index)
        self.method_index: dict[str, list] = {}
        #: (path, name) -> fnkey for module-level functions
        self.module_funcs: dict[tuple, str] = {}
        #: module dotted path -> relpath ("h2o_tpu.serving.stats" -> file)
        self.module_paths: dict[str, str] = {}
        for path, summ in self.files.items():
            mod = path[:-3].replace("/", ".") if path.endswith(".py") \
                else path
            self.module_paths[mod] = path
            if mod.endswith(".__init__"):
                self.module_paths[mod[:-9]] = path
            for cname, crec in summ.get("classes", {}).items():
                self.classes[(path, cname)] = crec
            for qual, fn in summ.get("functions", {}).items():
                key = f"{path}::{qual}"
                rec = dict(fn)
                rec["path"] = path
                self.functions[key] = rec
                if fn.get("cls"):
                    self.method_index.setdefault(fn["name"], []).append(key)
                elif "." not in qual and qual != "<module>":
                    self.module_funcs[(path, qual)] = key

    # -- resolution -----------------------------------------------------------
    def resolve_call(self, caller_key: str, kind: str, name: str,
                     recv: str | None) -> str | None:
        """Memoized — the dataflow pass resolves the same (caller, callee)
        pairs once per summary query, and the dotted suffix-scan is the
        single hottest operation of a warm full-repo run."""
        cache = getattr(self, "_resolve_cache", None)
        if cache is None:
            cache = self._resolve_cache = {}
        ck = (caller_key, kind, name, recv)
        if ck in cache:
            return cache[ck]
        out = self._resolve_call(caller_key, kind, name, recv)
        cache[ck] = out
        return out

    def _resolve_call(self, caller_key: str, kind: str, name: str,
                      recv: str | None) -> str | None:
        fn = self.functions.get(caller_key)
        if fn is None:
            return None
        path = fn["path"]
        if kind == "self":
            cls = fn.get("cls")
            if cls and (path, cls) in self.classes \
                    and name in self.classes[(path, cls)]["methods"]:
                prefix = self.classes[(path, cls)]["qual"]
                return f"{path}::{prefix}.{name}"
            return self._unique_method(name)
        if kind == "name":
            # own nested def, then lexical ancestors' nested defs (a
            # closure calls its SIBLING closures through the enclosing
            # scope — the `_dispatch` -> `_step_args` shape), then
            # module function. CLASS scopes are skipped: python never
            # resolves a bare name through the enclosing class body, so
            # `helper(x)` inside C.method must not resolve to C.helper
            # (that edge would shadow a real module-level `helper` and
            # fabricate call-graph facts downstream)
            qual = fn["qual"]
            cls_quals = self._class_quals(path)
            while True:
                if qual not in cls_quals:
                    key = f"{path}::{qual}.{name}"
                    if key in self.functions:
                        return key
                if "." not in qual:
                    break
                qual = qual.rsplit(".", 1)[0]
            return self.module_funcs.get((path, name))
        if kind == "dotted":
            # "telemetry.inc" with telemetry -> h2o_tpu.utils.telemetry;
            # relative imports resolve by unique module-path suffix
            head, _, meth = name.rpartition(".")
            target_path = self.module_paths.get(head)
            if target_path is None and head:
                cands = {p for m, p in self.module_paths.items()
                         if m == head or m.endswith("." + head)}
                if len(cands) == 1:
                    target_path = next(iter(cands))
            if target_path is not None:
                return self.module_funcs.get((target_path, meth))
            return None
        if kind == "attr":
            return self._unique_method(name)
        return None

    def _class_quals(self, path: str) -> frozenset:
        """Qual prefixes in ``path`` that are CLASS scopes (memoized) —
        the bare-name resolution walk must step over them."""
        cache = getattr(self, "_cls_quals_cache", None)
        if cache is None:
            cache = self._cls_quals_cache = {}
        got = cache.get(path)
        if got is None:
            got = cache[path] = frozenset(
                rec["qual"] for (p, _c), rec in self.classes.items()
                if p == path)
        return got

    def _unique_method(self, name: str) -> str | None:
        if name in _RESOLVE_BLOCKLIST:
            return None
        keys = self.method_index.get(name, [])
        return keys[0] if len(keys) == 1 else None

    def resolve_ref(self, caller_key: str, ref: str | None) -> str | None:
        """Resolve a spawn-target reference string to an fnkey."""
        if ref is None:
            return None
        if ref.startswith("local:"):
            fn = self.functions.get(caller_key)
            if fn is None:
                return None
            return f"{fn['path']}::{ref[6:]}" \
                if f"{fn['path']}::{ref[6:]}" in self.functions else None
        if ref.startswith("self."):
            return self.resolve_call(caller_key, "self", ref[5:], None)
        if ref.startswith("name:"):
            return self.resolve_call(caller_key, "name", ref[5:], None)
        if ref.startswith("dotted:"):
            return self.resolve_call(caller_key, "dotted", ref[7:], None)
        return None

    # -- thread-entry map -----------------------------------------------------
    def thread_roots(self) -> dict[str, str]:
        """{fnkey: root description} — every function that starts life on
        a non-main thread."""
        roots: dict[str, str] = {}
        for key, fn in self.functions.items():
            for ref, _store, line, _kind in fn.get("spawns", []):
                tgt = self.resolve_ref(key, ref)
                if tgt is not None and tgt in self.functions:
                    roots.setdefault(
                        tgt, f"spawned at {fn['path']}:{line}")
            if "rest-handler" in fn.get("root_hints", []):
                roots.setdefault(key, "REST handler thread")
        return roots

    def thread_reachable(self) -> dict[str, str]:
        """Closure of thread roots over the call graph:
        {fnkey: originating root description}."""
        roots = self.thread_roots()
        out: dict[str, str] = dict(roots)
        stack = list(roots)
        while stack:
            cur = stack.pop()
            fn = self.functions.get(cur)
            if fn is None:
                continue
            for kind, name, recv, _g, _line in fn.get("calls", []):
                tgt = self.resolve_call(cur, kind, name, recv)
                if tgt is not None and tgt not in out:
                    out[tgt] = out[cur]
                    stack.append(tgt)
        return out

    # -- lock identity --------------------------------------------------------
    def lock_id(self, fnkey: str, token: str) -> str | None:
        """Global lock node id for a held/acquired token, or None when the
        token is ambiguous (kept out of the cycle graph)."""
        fn = self.functions.get(fnkey)
        if fn is None:
            return None
        path = fn["path"]
        if token.startswith("self."):
            cls = fn.get("cls") or "?"
            return f"{path}::{cls}.{token[5:]}"
        if token.startswith("mod:"):
            return f"{path}::{token[4:]}"
        if token.startswith("ext:"):
            attr = token[4:]
            owners = [(p, c) for (p, c), rec in self.classes.items()
                      if attr in rec.get("locks", [])]
            if len(owners) == 1:
                return f"{owners[0][0]}::{owners[0][1]}.{attr}"
            return None
        return None
