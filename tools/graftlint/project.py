"""graftlint pass 1 — the repo-wide project model the interprocedural
rules (tools/graftlint/concurrency.py) analyze.

Per-file extraction produces a plain-dict **FileSummary** (JSON-able, so
the incremental cache under ``.graftlint_cache/`` can persist it keyed on
content hash): every function/method with the ``self.*`` fields it reads
and writes, the guards (locks) held at each access, the calls it makes,
the locks it acquires, the threads it spawns and joins. A **ProjectModel**
assembles all summaries into:

- a symbol table (module functions, class methods, per-class lock attrs);
- an approximate **call graph** — ``self.m()`` resolves within the class,
  bare/imported names resolve through the per-file import map, and
  ``obj.m()`` resolves through a *unique-method-name* index (if exactly
  one class in the project defines ``m`` and the name is not on the
  common-name blocklist, the edge is taken — deliberately
  under-approximate: an unresolved call produces no edge, never a wrong
  one... except where a non-unique spelling collides, which the blocklist
  exists to prevent);
- a **thread-entry map**: every ``threading.Thread(target=...)`` (and
  ``Timer``), every callable handed to a ``.start(fn)``-shaped job/worker
  dispatch, every ``do_*`` method of a ``BaseHTTPRequestHandler``
  subclass (REST handler threads — ThreadingHTTPServer runs each request
  on its own thread), and every callable registered through an
  ``add_*hook``/``register_*hook`` call (Cleaner sweep hooks) is a thread
  root; the transitive closure over the call graph is the code that runs
  on a non-main thread.

Guard tracking: ``with self._lock:`` / ``with _MODULE_LOCK:`` scopes push
a lock token for their body; a bare ``x.acquire(...)`` holds its token
for the remainder of the enclosing block (the try/finally idiom). Tokens:

- ``self.<attr>``   — instance lock (normalized per-class in the model)
- ``mod:<NAME>``    — module-level lock of the same file
- ``ext:<attr>``    — a lock attribute on some OTHER object (``vec._lock``
  in the Cleaner) — resolved per-class only when the attr names a lock in
  exactly one class, else kept out of the cycle graph (ambiguous nodes
  would merge distinct locks and fabricate cycles)

Nested functions/lambdas are extracted as their OWN functions (their
bodies run when called, not where defined — guards at the definition site
do not apply), inheriting the enclosing class context so a worker closure
that captures ``self`` still attributes its field accesses to the class
(the `Job.start._run` shape).

Stdlib ``ast`` only — the linter never imports the package it lints.
"""

from __future__ import annotations

import ast
import os

from .core import collect_aliases, normalize, dotted_name

#: bump when the summary shape changes — the incremental cache keys on it
SUMMARY_FORMAT = 3

#: constructors whose result is a lock-like guard (Condition guards too:
#: `with self._cv:` owns the underlying lock)
_LOCK_CTOR_SUFFIXES = ("threading.Lock", "threading.RLock",
                       "threading.Condition", "sanitizer.make_lock",
                       "make_lock")
#: constructors of non-lock sync primitives — exempt from field analysis
#: (an Event is its own synchronization, not shared data)
_SYNC_CTOR_SUFFIXES = _LOCK_CTOR_SUFFIXES + (
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "contextvars.ContextVar")

#: attr spellings treated as locks even without a visible declaration
#: (helper classes whose __init__ lives in another file)
_LOCKISH_ATTRS = ("lock", "mutex", "_cv", "cv")

#: method names too common to resolve through the unique-name index — a
#: wrong edge is worse than a missing one
_RESOLVE_BLOCKLIST = {
    "get", "put", "set", "add", "pop", "append", "extend", "remove",
    "clear", "copy", "update", "items", "keys", "values", "join", "split",
    "strip", "encode", "decode", "format", "index", "count", "insert",
    "sort", "read", "write", "close", "open", "flush", "seek", "tell",
    "start", "stop", "run", "send", "recv", "acquire", "release", "wait",
    "notify", "notify_all", "is_set", "mkdir", "exists", "search",
    "match", "group", "lower", "upper", "replace", "startswith",
    "endswith", "info", "keys", "name", "next", "reset", "submit",
}


def _lockish(attr: str) -> bool:
    a = attr.lower()
    return any(t in a for t in _LOCKISH_ATTRS)


def _is_lock_ctor(node: ast.AST, aliases: dict) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = normalize(dotted_name(node.func), aliases)
    return bool(fn) and fn.endswith(_LOCK_CTOR_SUFFIXES)


def _is_sync_ctor(node: ast.AST, aliases: dict) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = normalize(dotted_name(node.func), aliases)
    return bool(fn) and fn.endswith(_SYNC_CTOR_SUFFIXES)


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self.x` attribute node, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _FnState:
    """Mutable record of one function's summary while extracting."""

    def __init__(self, qual: str, cls: str | None, name: str, line: int):
        self.qual = qual
        self.cls = cls
        self.name = name
        self.line = line
        self.reads: list = []       # [field, [guards], line]
        self.writes: list = []      # [field, [guards], line]
        self.calls: list = []       # [kind, name, recv, [guards], line]
        self.acquires: list = []    # [token, [held], line]
        self.spawns: list = []      # [target_ref, store_attr, line]
        self.joins: list = []       # tokens joined ("self._worker", "L")
        self.root_hints: list = []  # ["rest-handler"]
        self.locals_alias: dict[str, str] = {}   # local -> "self.attr"
        self.local_threads: set[str] = set()     # locals holding a Thread

    def summary(self) -> dict:
        return {"qual": self.qual, "cls": self.cls, "name": self.name,
                "public": not self.name.startswith("_"),
                "line": self.line, "reads": self.reads,
                "writes": self.writes, "calls": self.calls,
                "acquires": self.acquires, "spawns": self.spawns,
                "joins": sorted(set(self.joins)),
                "root_hints": self.root_hints}


class _Extractor:
    """Per-file AST walk → FileSummary dict."""

    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        self.aliases = collect_aliases(tree)
        self.module_locks: set[str] = set()
        self.functions: dict[str, dict] = {}
        self.classes: dict[str, dict] = {}
        self._collect_module_locks()

    def _collect_module_locks(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value,
                                                              self.aliases):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)

    # -- class prep -----------------------------------------------------------
    def _class_lock_attrs(self, cls: ast.ClassDef) -> tuple[set, set]:
        """(lock attrs, all sync attrs) declared anywhere in the class via
        `self.x = threading.Lock()/.../sanitizer.make_lock(...)`."""
        locks: set[str] = set()
        syncs: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if _is_lock_ctor(node.value, self.aliases):
                    locks.add(attr)
                if _is_sync_ctor(node.value, self.aliases):
                    syncs.add(attr)
        return locks, syncs

    # -- extraction -----------------------------------------------------------
    def extract(self) -> dict:
        # module body as a pseudo-function (module-level spawns/locks);
        # top-level defs are extracted by _walk_top below, not here
        mod_stmts = [s for s in self.tree.body
                     if not isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))]
        self._extract_scope(mod_stmts, "<module>", None, "<module>", 1,
                            class_locks=set(), class_syncs=set())
        for node in self.tree.body:
            self._walk_top(node, prefix="")
        return {
            "path": self.relpath,
            "format": SUMMARY_FORMAT,
            "module_locks": sorted(self.module_locks),
            "functions": self.functions,
            "classes": self.classes,
        }

    def _walk_top(self, node: ast.AST, prefix: str,
                  cls_ctx: str | None = None,
                  class_locks: set | None = None,
                  class_syncs: set | None = None) -> None:
        if isinstance(node, ast.ClassDef):
            locks, syncs = self._class_lock_attrs(node)
            bases = [dotted_name(b) or "" for b in node.bases]
            qual = f"{prefix}{node.name}"
            self.classes[node.name] = {
                "qual": qual, "locks": sorted(locks),
                "bases": bases, "methods": [], "line": node.lineno,
            }
            handler = any(b.split(".")[-1] == "BaseHTTPRequestHandler"
                          for b in bases)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    st = self._extract_scope(
                        sub.body, f"{qual}.{sub.name}", node.name, sub.name,
                        sub.lineno, class_locks=locks, class_syncs=syncs)
                    if handler and sub.name.startswith("do_"):
                        st.root_hints.append("rest-handler")
                    self.classes[node.name]["methods"].append(sub.name)
                else:
                    self._walk_top(sub, prefix=f"{qual}.")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._extract_scope(node.body, f"{prefix}{node.name}", cls_ctx,
                                node.name, node.lineno,
                                class_locks=class_locks or set(),
                                class_syncs=class_syncs or set())
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for sub in ast.iter_child_nodes(node):
                self._walk_top(sub, prefix, cls_ctx, class_locks,
                               class_syncs)

    # -- one function body ----------------------------------------------------
    def _extract_scope(self, body: list, qual: str, cls: str | None,
                       name: str, line: int, *, class_locks: set,
                       class_syncs: set) -> _FnState:
        st = _FnState(qual, cls, name, line)
        self._nested: list[tuple] = []
        self._walk_block(body, (), st, class_locks, class_syncs)
        self.functions[qual] = st.summary()
        # nested defs extracted AFTER the parent (guards do not inherit:
        # a closure body runs when called, not where defined)
        for sub, subqual in self._pop_nested():
            sub_body = (sub.body if isinstance(sub, (ast.FunctionDef,
                                                     ast.AsyncFunctionDef))
                        else [ast.Expr(value=sub.body)])
            self._extract_scope(sub_body, subqual, cls,
                                subqual.rsplit(".", 1)[-1],
                                getattr(sub, "lineno", line),
                                class_locks=class_locks,
                                class_syncs=class_syncs)
        return st

    def _pop_nested(self):
        out, self._nested = self._nested, []
        return out

    def _lock_token(self, expr: ast.AST, st: _FnState,
                    class_locks: set) -> str | None:
        """Lock token for a with-item / acquire receiver, or None when the
        expression is not lock-like."""
        attr = _self_attr(expr)
        if attr is not None:
            if attr in class_locks or _lockish(attr):
                return f"self.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks or _lockish(expr.id):
                return f"mod:{expr.id}"
            return None
        if isinstance(expr, ast.Attribute) and _lockish(expr.attr):
            return f"ext:{expr.attr}"
        return None

    def _walk_block(self, stmts: list, guards: tuple, st: _FnState,
                    class_locks: set, class_syncs: set) -> None:
        guards = tuple(guards)
        for stmt in stmts:
            guards = self._walk_stmt(stmt, guards, st, class_locks,
                                     class_syncs)

    def _walk_stmt(self, stmt: ast.AST, guards: tuple, st: _FnState,
                   class_locks: set, class_syncs: set) -> tuple:
        """Process one statement; returns the guard set for the NEXT
        statement in the block (a bare `.acquire()` extends it)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested.append((stmt, f"{st.qual}.{stmt.name}"))
            return guards
        if isinstance(stmt, ast.ClassDef):
            self._walk_top(stmt, prefix=f"{st.qual}.")
            return guards
        if isinstance(stmt, ast.With):
            inner = list(guards)
            for item in stmt.items:
                tok = self._lock_token(item.context_expr, st, class_locks)
                if tok is not None:
                    st.acquires.append([tok, list(inner), stmt.lineno,
                                        True])
                    inner.append(tok)
                self._scan_expr(item.context_expr, guards, st, class_locks,
                                class_syncs)
            self._walk_block(stmt.body, tuple(inner), st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, ast.Try):
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            for h in stmt.handlers:
                self._walk_block(h.body, guards, st, class_locks,
                                 class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            self._walk_block(stmt.finalbody, guards, st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            return guards
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            # `for t in threads:` over a local thread list — joins on the
            # loop variable drain the whole list
            if (isinstance(stmt.iter, ast.Name)
                    and stmt.iter.id in st.local_threads
                    and isinstance(stmt.target, ast.Name)):
                st.locals_alias[stmt.target.id] = f"localiter:{stmt.iter.id}"
            self._scan_expr(stmt.iter, guards, st, class_locks, class_syncs)
            self._scan_expr(stmt.target, guards, st, class_locks,
                            class_syncs)
            self._walk_block(stmt.body, guards, st, class_locks, class_syncs)
            self._walk_block(stmt.orelse, guards, st, class_locks,
                             class_syncs)
            return guards
        # simple statement: scan expressions, track aliases/acquire
        new_guards = self._scan_simple(stmt, guards, st, class_locks,
                                       class_syncs)
        return new_guards

    def _scan_simple(self, stmt: ast.AST, guards: tuple, st: _FnState,
                     class_locks: set, class_syncs: set) -> tuple:
        # local alias tracking: `w = self._shadow_worker`
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = stmt.targets[0].id
            src_attr = _self_attr(stmt.value)
            if src_attr is not None:
                st.locals_alias[tgt] = f"self.{src_attr}"
        self._scan_expr(stmt, guards, st, class_locks, class_syncs)
        # a bare `<lock>.acquire(...)` holds for the rest of the block;
        # `.release()` drops it (the try/finally idiom — approximate)
        out = list(guards)
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            tok = self._lock_token(node.func.value, st, class_locks)
            if tok is None:
                continue
            if node.func.attr == "acquire":
                blocking = True
                for kw in node.keywords:
                    if (kw.arg == "blocking"
                            and isinstance(kw.value, ast.Constant)):
                        blocking = bool(kw.value.value)
                if node.args and isinstance(node.args[0], ast.Constant):
                    blocking = bool(node.args[0].value)
                # non-blocking acquires still HOLD on success — they are
                # an edge source but never an inversion victim; keep them
                # as held guards, the cycle rule cares about order only
                st.acquires.append([tok, list(out), node.lineno,
                                    blocking])
                if tok not in out:
                    out.append(tok)
            elif node.func.attr == "release" and tok in out:
                out.remove(tok)
        return tuple(out)

    def _scan_expr(self, root: ast.AST, guards: tuple, st: _FnState,
                   class_locks: set, class_syncs: set) -> None:
        """Collect field accesses / calls / spawns from an expression tree
        without descending into nested function scopes."""
        stack = [(root, "load")]
        while stack:
            node, mode = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._nested.append((node, f"{st.qual}.{node.name}"))
                continue
            if isinstance(node, ast.Lambda):
                self._nested.append(
                    (node, f"{st.qual}.<lambda:{node.lineno}>"))
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    stack.append((t, "store"))
                stack.append((node.value, "load"))
                self._check_spawn_store(node, st, guards)
                continue
            if isinstance(node, ast.AugAssign):
                stack.append((node.target, "both"))
                stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.AnnAssign):
                if node.target is not None:
                    stack.append((node.target, "store"))
                if node.value is not None:
                    stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None and attr not in class_syncs \
                        and not (attr in class_locks or _lockish(attr)):
                    g = list(guards)
                    if mode in ("store", "both"):
                        st.writes.append([attr, g, node.lineno])
                    if mode in ("load", "both"):
                        st.reads.append([attr, g, node.lineno])
                stack.append((node.value, "load"))
                continue
            if isinstance(node, ast.Call):
                self._record_call(node, guards, st, class_locks)
                for sub in ast.iter_child_nodes(node):
                    stack.append((sub, "load"))
                continue
            for sub in ast.iter_child_nodes(node):
                stack.append((sub, mode if isinstance(node, (ast.Tuple,
                                                             ast.List))
                              else "load"))

    # -- call / spawn recording ----------------------------------------------
    def _callable_ref(self, node: ast.AST, st: _FnState) -> str | None:
        """Reference string for a callable expression (thread target /
        dispatched worker fn)."""
        attr = _self_attr(node)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(node, ast.Name):
            return f"name:{node.id}"
        if isinstance(node, ast.Lambda):
            self._nested.append((node, f"{st.qual}.<lambda:{node.lineno}>"))
            return f"local:{st.qual}.<lambda:{node.lineno}>"
        dn = dotted_name(node)
        if dn:
            return f"dotted:{dn}"
        return None

    def _check_spawn_store(self, assign: ast.Assign, st: _FnState,
                           guards: tuple) -> None:
        """`self.X = threading.Thread(...)` / `t = threading.Thread(...)`
        / `threads = [threading.Thread(...) for ...]` — record the storage
        so joins (incl. `for t in threads: t.join()`) can be matched."""
        call = assign.value
        if isinstance(call, (ast.ListComp, ast.GeneratorExp)):
            inner = next((n for n in ast.walk(call.elt)
                          if isinstance(n, ast.Call)
                          and (normalize(dotted_name(n.func), self.aliases)
                               or "").endswith(("threading.Thread",
                                                "threading.Timer"))), None)
            if inner is not None:
                for t in assign.targets:
                    if isinstance(t, ast.Name):
                        st.local_threads.add(t.id)
                        self._note_spawn(inner, st, store=f"local:{t.id}")
                        return
            return
        if not isinstance(call, ast.Call):
            return
        fn = normalize(dotted_name(call.func), self.aliases)
        if not fn or not fn.endswith(("threading.Thread",
                                      "threading.Timer")):
            return
        for t in assign.targets:
            attr = _self_attr(t)
            if attr is not None:
                self._note_spawn(call, st, store=f"self.{attr}")
                return
            if isinstance(t, ast.Name):
                st.local_threads.add(t.id)
                self._note_spawn(call, st, store=f"local:{t.id}")
                return
        self._note_spawn(call, st, store=None)

    def _note_spawn(self, call: ast.Call, st: _FnState,
                    store: str | None) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = self._callable_ref(kw.value, st)
        if target is None and call.args:
            target = self._callable_ref(call.args[0], st)
        # dedupe: _record_call sees the same Call node again
        for sp in st.spawns:
            if sp[2] == call.lineno:
                return
        st.spawns.append([target, store, call.lineno, "thread"])

    def _record_call(self, node: ast.Call, guards: tuple,
                     st: _FnState, class_locks: set) -> None:
        fn = normalize(dotted_name(node.func), self.aliases)
        line = node.lineno
        g = list(guards)
        # thread spawn (anonymous / unstored form)
        if fn and fn.endswith(("threading.Thread", "threading.Timer")):
            self._note_spawn(node, st, store=None)
            return
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            recv = None
            a = _self_attr(node.func.value)
            if a is not None:
                recv = f"self.{a}"
            elif isinstance(node.func.value, ast.Name):
                nm = node.func.value.id
                recv = st.locals_alias.get(nm, f"name:{nm}")
            elif isinstance(node.func.value, ast.Constant):
                recv = "literal"
            # join bookkeeping for unjoined-thread
            if meth == "join" and recv and recv != "literal":
                if recv.startswith("self."):
                    st.joins.append(recv)
                elif (recv.startswith("name:")
                        and recv[5:] in st.local_threads):
                    st.joins.append(f"local:{recv[5:]}")
                elif recv.startswith("localiter:"):
                    st.joins.append(f"local:{recv[10:]}")
            # `.start(fn)` with a callable argument = a worker dispatch
            # (Thread.start takes no args, so this is Job.start-shaped)
            if meth == "start" and node.args:
                ref = self._callable_ref(node.args[0], st)
                if ref is not None:
                    st.spawns.append([ref, None, line, "dispatch"])
            # hook registration: the callable runs on someone else's thread
            if (("hook" in meth and meth.startswith(("add_", "register_")))
                    and node.args):
                ref = self._callable_ref(node.args[0], st)
                if ref is not None:
                    st.spawns.append([ref, None, line, "dispatch"])
            if self._self_call(node, st):
                st.calls.append(["self", meth, None, g, line])
            elif fn is not None:
                st.calls.append(["dotted", fn, recv, g, line])
            else:
                st.calls.append(["attr", meth, recv, g, line])
        elif isinstance(node.func, ast.Name):
            st.calls.append(["name", node.func.id, None, g, line])
        elif fn is not None:
            st.calls.append(["dotted", fn, None, g, line])

    @staticmethod
    def _self_call(node: ast.Call, st: _FnState) -> bool:
        return (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self")


def extract_summary(relpath: str, source: str) -> dict | None:
    """FileSummary for one source file (None on syntax errors — the
    per-file rules report those)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    return _Extractor(relpath.replace(os.sep, "/"), tree).extract()


# ---------------------------------------------------------------------------
# the assembled model
# ---------------------------------------------------------------------------
class ProjectModel:
    """All file summaries resolved into one queryable graph."""

    def __init__(self, summaries: dict[str, dict]):
        #: path -> summary (insertion order = scan order; keep sorted)
        self.files = {p: s for p, s in sorted(summaries.items())
                      if s is not None}
        #: fnkey ("path::qual") -> function summary (+ "path")
        self.functions: dict[str, dict] = {}
        #: (path, class) -> class record
        self.classes: dict[tuple, dict] = {}
        #: method name -> [fnkey] across all classes (unique-name index)
        self.method_index: dict[str, list] = {}
        #: (path, name) -> fnkey for module-level functions
        self.module_funcs: dict[tuple, str] = {}
        #: module dotted path -> relpath ("h2o_tpu.serving.stats" -> file)
        self.module_paths: dict[str, str] = {}
        for path, summ in self.files.items():
            mod = path[:-3].replace("/", ".") if path.endswith(".py") \
                else path
            self.module_paths[mod] = path
            if mod.endswith(".__init__"):
                self.module_paths[mod[:-9]] = path
            for cname, crec in summ.get("classes", {}).items():
                self.classes[(path, cname)] = crec
            for qual, fn in summ.get("functions", {}).items():
                key = f"{path}::{qual}"
                rec = dict(fn)
                rec["path"] = path
                self.functions[key] = rec
                if fn.get("cls"):
                    self.method_index.setdefault(fn["name"], []).append(key)
                elif "." not in qual and qual != "<module>":
                    self.module_funcs[(path, qual)] = key

    # -- resolution -----------------------------------------------------------
    def resolve_call(self, caller_key: str, kind: str, name: str,
                     recv: str | None) -> str | None:
        fn = self.functions.get(caller_key)
        if fn is None:
            return None
        path = fn["path"]
        if kind == "self":
            cls = fn.get("cls")
            if cls and (path, cls) in self.classes \
                    and name in self.classes[(path, cls)]["methods"]:
                prefix = self.classes[(path, cls)]["qual"]
                return f"{path}::{prefix}.{name}"
            return self._unique_method(name)
        if kind == "name":
            # nested def of the same function, then module function
            key = f"{path}::{fn['qual']}.{name}"
            if key in self.functions:
                return key
            return self.module_funcs.get((path, name))
        if kind == "dotted":
            # "telemetry.inc" with telemetry -> h2o_tpu.utils.telemetry;
            # relative imports resolve by unique module-path suffix
            head, _, meth = name.rpartition(".")
            target_path = self.module_paths.get(head)
            if target_path is None and head:
                cands = {p for m, p in self.module_paths.items()
                         if m == head or m.endswith("." + head)}
                if len(cands) == 1:
                    target_path = next(iter(cands))
            if target_path is not None:
                return self.module_funcs.get((target_path, meth))
            return None
        if kind == "attr":
            return self._unique_method(name)
        return None

    def _unique_method(self, name: str) -> str | None:
        if name in _RESOLVE_BLOCKLIST:
            return None
        keys = self.method_index.get(name, [])
        return keys[0] if len(keys) == 1 else None

    def resolve_ref(self, caller_key: str, ref: str | None) -> str | None:
        """Resolve a spawn-target reference string to an fnkey."""
        if ref is None:
            return None
        if ref.startswith("local:"):
            fn = self.functions.get(caller_key)
            if fn is None:
                return None
            return f"{fn['path']}::{ref[6:]}" \
                if f"{fn['path']}::{ref[6:]}" in self.functions else None
        if ref.startswith("self."):
            return self.resolve_call(caller_key, "self", ref[5:], None)
        if ref.startswith("name:"):
            return self.resolve_call(caller_key, "name", ref[5:], None)
        if ref.startswith("dotted:"):
            return self.resolve_call(caller_key, "dotted", ref[7:], None)
        return None

    # -- thread-entry map -----------------------------------------------------
    def thread_roots(self) -> dict[str, str]:
        """{fnkey: root description} — every function that starts life on
        a non-main thread."""
        roots: dict[str, str] = {}
        for key, fn in self.functions.items():
            for ref, _store, line, _kind in fn.get("spawns", []):
                tgt = self.resolve_ref(key, ref)
                if tgt is not None and tgt in self.functions:
                    roots.setdefault(
                        tgt, f"spawned at {fn['path']}:{line}")
            if "rest-handler" in fn.get("root_hints", []):
                roots.setdefault(key, "REST handler thread")
        return roots

    def thread_reachable(self) -> dict[str, str]:
        """Closure of thread roots over the call graph:
        {fnkey: originating root description}."""
        roots = self.thread_roots()
        out: dict[str, str] = dict(roots)
        stack = list(roots)
        while stack:
            cur = stack.pop()
            fn = self.functions.get(cur)
            if fn is None:
                continue
            for kind, name, recv, _g, _line in fn.get("calls", []):
                tgt = self.resolve_call(cur, kind, name, recv)
                if tgt is not None and tgt not in out:
                    out[tgt] = out[cur]
                    stack.append(tgt)
        return out

    # -- lock identity --------------------------------------------------------
    def lock_id(self, fnkey: str, token: str) -> str | None:
        """Global lock node id for a held/acquired token, or None when the
        token is ambiguous (kept out of the cycle graph)."""
        fn = self.functions.get(fnkey)
        if fn is None:
            return None
        path = fn["path"]
        if token.startswith("self."):
            cls = fn.get("cls") or "?"
            return f"{path}::{cls}.{token[5:]}"
        if token.startswith("mod:"):
            return f"{path}::{token[4:]}"
        if token.startswith("ext:"):
            attr = token[4:]
            owners = [(p, c) for (p, c), rec in self.classes.items()
                      if attr in rec.get("locks", [])]
            if len(owners) == 1:
                return f"{owners[0][0]}::{owners[0][1]}.{attr}"
            return None
        return None
