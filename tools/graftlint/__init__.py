"""graftlint — repo-native static analysis for h2o_tpu's JAX hazard classes.

CLI:    python -m tools.graftlint [paths ...] [--fix] [--baseline-update]
Gate:   tests/test_graftlint.py (tier-1, marker `graftlint`)
Rules:  tools/graftlint/rules.py (catalog + incident history);
        tools/graftlint/concurrency.py (interprocedural pass 2);
        tools/graftlint/dataflow.py (array-provenance pass 3)
"""

from .concurrency import PROJECT_RULES, lint_project
from .core import (BASELINE_PATH, CACHE_DIR, DEFAULT_PATHS, REPO_ROOT,
                   FileContext, Rule, Violation, apply_baseline, lint_paths,
                   lint_source, load_baseline, main, render_github,
                   render_sarif, write_baseline)
from .dataflow import DATAFLOW_RULES
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES", "BASELINE_PATH", "CACHE_DIR", "DATAFLOW_RULES",
    "DEFAULT_PATHS", "PROJECT_RULES", "REPO_ROOT", "FileContext", "Rule",
    "Violation", "apply_baseline", "lint_paths", "lint_project",
    "lint_source", "load_baseline", "main", "render_github", "render_sarif",
    "write_baseline",
]
