"""graftlint — repo-native static analysis for h2o_tpu's JAX hazard classes.

CLI:    python -m tools.graftlint [paths ...] [--fix] [--baseline-update]
Gate:   tests/test_graftlint.py (tier-1, marker `graftlint`)
Rules:  tools/graftlint/rules.py (catalog + incident history)
"""

from .core import (BASELINE_PATH, DEFAULT_PATHS, REPO_ROOT, FileContext,
                   Rule, Violation, apply_baseline, lint_paths, lint_source,
                   load_baseline, main, write_baseline)
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES", "BASELINE_PATH", "DEFAULT_PATHS", "REPO_ROOT",
    "FileContext", "Rule", "Violation", "apply_baseline", "lint_paths",
    "lint_source", "load_baseline", "main", "write_baseline",
]
