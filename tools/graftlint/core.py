"""graftlint core — the rule framework, file runner, cache, baseline.

A repo-native static analyzer: 15 per-file AST rules plus 8 interprocedural
rules — 4 concurrency (pass 2) and 4 array-provenance dataflow (pass 3) —
encoding hazard classes this codebase has actually hit (see
`tools/graftlint/rules.py`, `tools/graftlint/concurrency.py`, and
`tools/graftlint/dataflow.py` for the catalogs and ISSUE/README for the
history). Deliberately
dependency-free — stdlib ``ast`` only, no jax import, so the lint gate
costs ~a second cold and much less warm, and runs identically on a dev
laptop and in the tier-1 pytest tier.

Mechanics:

- every per-file rule is a `Rule` subclass with a stable kebab-case
  ``id``; a run parses each file once and hands the tree + a per-file
  `FileContext` (import-alias map, traced-scope set, suppression table)
  to every rule;
- the interprocedural rules run over per-file summaries (`project.py`
  pass 1 → `concurrency.py` pass 2 → `dataflow.py` pass 3);
- **incremental cache**: per-file results (violations + project summary)
  persist under ``.graftlint_cache/`` keyed on (content hash, rule-set
  version, selected rules). The rule-set version hashes every
  tools/graftlint source — including `dataflow.py` and the provenance
  event shapes in `project.py`, so a stale cache can never hide a
  new-rule finding — AND the three registry files (knobs / failpoints /
  telemetry) the registry rules read, so editing a registry invalidates
  every cached file. The pass-2/3 project analyses re-run every time
  from the (cached) summaries — they are repo-global by nature and cost
  ~0.1 s;
- ``--jobs N`` scans cache misses in parallel;
- inline suppressions: ``# graftlint: disable=<rule>[,<rule>...]`` (or
  bare ``disable`` for all rules) on any physical line of the flagged
  statement (interprocedural findings: on the flagged line);
- the checked-in ``tools/graftlint/baseline.json`` grandfathers
  pre-existing violations: entries match on (rule, path, stripped source
  line), so line drift from unrelated edits does not resurrect them;
- ``--baseline-update`` regenerates the file deterministically (sorted,
  path-relative, reasons preserved) so baseline diffs stay reviewable;
- ``--format sarif|github`` emit machine-readable findings (SARIF 2.1.0 /
  GitHub workflow commands) for CI annotation; `tools/ci_gate.sh` runs
  the lint and the tier-1 pytest line as one exit-coded gate.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import sys

#: repo root = two levels above this file (tools/graftlint/core.py)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
#: default scan set — the CLI and the pytest gate lint the same tree
DEFAULT_PATHS = ("h2o_tpu", "tests", "bench.py")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=([A-Za-z0-9_\-, ]+))?")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based line of the flagged node
    col: int
    message: str
    snippet: str       # stripped source of the flagged line (baseline key)
    severity: str = "error"
    line_end: int = 0  # last physical line of the flagged node (0 = line)
    col_end: int = 0   # 0-based end column (ast end_col_offset; 0 = unknown)
                       # — SARIF regions carry it so GitHub annotations
                       # underline the expression, not just its first char

    def span(self) -> range:
        return range(self.line, max(self.line_end, self.line) + 1)

    def key(self) -> tuple:
        return (self.rule, self.path, self.snippet)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule}: {self.message}")


class Rule:
    """One lint rule. Subclasses set ``id``/``doc`` and implement
    ``check(tree, ctx) -> list[Violation]``."""

    id: str = ""
    severity: str = "error"
    doc: str = ""

    def check(self, tree: ast.Module, ctx: "FileContext") -> list[Violation]:
        raise NotImplementedError

    def violation(self, ctx: "FileContext", node: ast.AST,
                  message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        return Violation(rule=self.id, path=ctx.relpath, line=line,
                         col=getattr(node, "col_offset", 0), message=message,
                         snippet=ctx.line_text(line), severity=self.severity,
                         line_end=getattr(node, "end_lineno", line) or line,
                         col_end=getattr(node, "end_col_offset", 0) or 0)


# ---------------------------------------------------------------------------
# Shared AST analyses (computed once per file, consumed by several rules).
# ---------------------------------------------------------------------------
def dotted_name(node: ast.AST) -> str | None:
    """'jax.experimental.shard_map.shard_map' for an Attribute/Name chain;
    None for anything rooted elsewhere (calls, subscripts, ...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def collect_aliases(tree: ast.Module) -> dict[str, str]:
    """Import-alias map: local name -> canonical dotted module. Covers the
    repo conventions (``import jax.numpy as jnp``, ``from jax import lax``,
    ``from jax.sharding import PartitionSpec as P``...)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def normalize(dotted: str | None, aliases: dict[str, str]) -> str | None:
    """Rewrite the first segment through the alias map, then collapse the
    well-known jax module spellings to canonical roots (jax.numpy -> jnp,
    jax.lax -> lax, numpy -> np) so rules match one spelling."""
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    full = aliases.get(head, head)
    out = f"{full}.{rest}" if rest else full
    for prefix, canon in (("jax.numpy", "jnp"), ("jax.lax", "lax"),
                          ("numpy", "np")):
        if out == prefix or out.startswith(prefix + "."):
            out = canon + out[len(prefix):]
    return out


#: call entry points whose function arguments are traced by jax
_TRACING_ENTRY_SUFFIXES = ("shard_map",)
_TRACING_ENTRY_NAMES = {
    "jax.jit", "jit", "lax.scan", "lax.fori_loop", "lax.while_loop",
    "lax.cond", "lax.switch", "lax.map", "lax.associative_scan",
    "jax.vmap", "vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.grad", "jax.value_and_grad",
}


def _is_tracing_entry(norm: str | None) -> bool:
    if norm is None:
        return False
    return (norm in _TRACING_ENTRY_NAMES
            or norm.endswith(_TRACING_ENTRY_SUFFIXES))


def traced_scopes(tree: ast.Module,
                  aliases: dict[str, str]) -> set[ast.AST]:
    """Function/lambda nodes whose bodies run under a jax trace: decorated
    with jit (bare, called, or partial(jax.jit, ...)), passed by name or
    inline to a tracing entry point (jit/scan/fori_loop/shard_map/vmap/...),
    or lexically nested inside such a function."""
    traced: set[ast.AST] = set()
    defs_by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    def decorator_traces(dec: ast.AST) -> bool:
        if _is_tracing_entry(normalize(dotted_name(dec), aliases)):
            return True
        if isinstance(dec, ast.Call):
            fn = normalize(dotted_name(dec.func), aliases)
            if _is_tracing_entry(fn):
                return True
            if fn in ("functools.partial", "partial") and dec.args:
                return _is_tracing_entry(
                    normalize(dotted_name(dec.args[0]), aliases))
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(decorator_traces(d) for d in node.decorator_list):
                traced.add(node)
        elif isinstance(node, ast.Call):
            if not _is_tracing_entry(
                    normalize(dotted_name(node.func), aliases)):
                continue
            cands = list(node.args) + [kw.value for kw in node.keywords
                                       if kw.arg in (None, "f", "fun", "body",
                                                     "body_fun", "cond_fun")]
            for arg in cands:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    traced.add(defs_by_name[arg.id][-1])

    # propagate: nested defs/lambdas inside a traced function are traced
    grew = True
    while grew:
        grew = False
        for fn in list(traced):
            for sub in ast.walk(fn):
                if (sub is not fn
                        and isinstance(sub, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda))
                        and sub not in traced):
                    traced.add(sub)
                    grew = True
    return traced


def function_scopes(tree: ast.Module) -> list[ast.AST]:
    """All function-like scopes plus the module itself."""
    out: list[ast.AST] = [tree]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            out.append(node)
    return out


def scope_statements(scope: ast.AST):
    """Walk a scope WITHOUT descending into nested function scopes (each
    nested scope is analyzed on its own)."""
    body = scope.body if not isinstance(scope, ast.Lambda) else [scope.body]
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # nested scope — analyzed on its own
        stack.extend(ast.iter_child_nodes(node))


def suppression_table(source: str) -> dict:
    """1-based line -> set of rule ids suppressed there (None = all) —
    shared by per-file FileContexts and the pass-2 project runner."""
    table: dict[int, set | None] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group(1) is None:
            table[i] = None
        else:
            table[i] = {r.strip() for r in m.group(1).split(",")
                        if r.strip()}
    return table


class FileContext:
    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.aliases = collect_aliases(tree)
        self.traced = traced_scopes(tree, self.aliases)
        # suppression table: 1-based line -> set of rule ids (None = all)
        self.suppressions = suppression_table(source)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if line not in self.suppressions:
            return False
        ids = self.suppressions[line]
        return ids is None or rule_id in ids


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------
def _all_rules() -> list[Rule]:
    from . import rules as rules_mod

    return [cls() for cls in rules_mod.ALL_RULES]


def lint_source(source: str, relpath: str = "<memory>.py",
                rules: list[Rule] | None = None) -> list[Violation]:
    """Lint one source string (fixture/test entry point). Suppressions
    apply; baseline does not."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(rule="syntax-error", path=relpath,
                          line=e.lineno or 1, col=(e.offset or 1) - 1,
                          message=str(e.msg), snippet="")]
    ctx = FileContext(relpath, source, tree)
    out: list[Violation] = []
    for rule in (rules if rules is not None else _all_rules()):
        for v in rule.check(tree, ctx):
            # a disable comment counts on ANY physical line of the flagged
            # statement (the natural place is often a continuation line)
            if not any(ctx.is_suppressed(v.rule, ln) for ln in v.span()):
                out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.col, v.rule))


def iter_py_files(paths, root: str = REPO_ROOT):
    """Yield absolute paths of .py files under ``paths`` (files or dirs,
    relative to ``root``), skipping __pycache__ and hidden dirs."""
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                yield ap
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


# ---------------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------------
CACHE_DIR = os.path.join(REPO_ROOT, ".graftlint_cache")

#: registry files whose content changes the RESULTS of per-file rules
#: (unregistered-knob/-failpoint/-metric read them) — they invalidate the
#: whole cache exactly like editing a rule does
_REGISTRY_FILES = ("h2o_tpu/utils/knobs.py", "h2o_tpu/utils/failpoints.py",
                   "h2o_tpu/utils/telemetry.py")

_RULESET_VERSIONS: dict[str, str] = {}


def ruleset_version(root: str = REPO_ROOT) -> str:
    """Hash of every tools/graftlint source plus the three registry files
    — the cache key component that invalidates on any rule change. Memo
    is keyed per ``root``: the registry files live under it, so a run
    against a fixture tree must not decide the version for the repo."""
    if root in _RULESET_VERSIONS:
        return _RULESET_VERSIONS[root]
    h = hashlib.sha1()
    tooldir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(tooldir)):
        if fn.endswith(".py"):
            h.update(fn.encode())
            with open(os.path.join(tooldir, fn), "rb") as f:
                h.update(f.read())
    for rel in _REGISTRY_FILES:
        ap = os.path.join(root, rel)
        h.update(rel.encode())
        if os.path.exists(ap):
            with open(ap, "rb") as f:
                h.update(f.read())
    _RULESET_VERSIONS[root] = h.hexdigest()
    return _RULESET_VERSIONS[root]


def _cache_path(rel: str, cache_dir: str) -> str:
    return os.path.join(cache_dir, rel.replace("/", "__") + ".json")


def _cache_load(rel: str, content_key: str, rules_sig: str,
                cache_dir: str, version: str):
    """(violations, summary) on a hit, None on any miss/mismatch."""
    try:
        with open(_cache_path(rel, cache_dir), encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if (data.get("content") != content_key
            or data.get("version") != version
            or data.get("rules") != rules_sig):
        return None
    vs = [Violation(**v) for v in data.get("violations", [])]
    return vs, data.get("summary")


def _cache_store(rel: str, content_key: str, rules_sig: str,
                 cache_dir: str, version: str, violations, summary) -> None:
    payload = {"content": content_key, "version": version,
               "rules": rules_sig,
               "violations": [dataclasses.asdict(v) for v in violations],
               "summary": summary}
    path = _cache_path(rel, cache_dir)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # atomic: parallel runs never read a torn file
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


#: per-process state for the --jobs worker pool (set by _worker_init)
_WORKER_STATE: dict = {}


def _worker_init(rule_ids, cache, cache_dir, version, rules_sig) -> None:
    from . import rules as rules_mod

    _WORKER_STATE["rules"] = [cls() for cls in rules_mod.ALL_RULES
                              if cls.id in set(rule_ids)]
    _WORKER_STATE.update(cache=cache, cache_dir=cache_dir,
                         version=version, rules_sig=rules_sig)


def _worker_scan(item):
    """One file's per-file scan inside a --jobs worker process."""
    rel, source, key = item
    from .concurrency import in_scope
    from .project import extract_summary

    st = _WORKER_STATE
    vs = lint_source(source, relpath=rel, rules=st["rules"])
    summary = extract_summary(rel, source) if in_scope(rel) else None
    if st["cache"]:
        _cache_store(rel, key, st["rules_sig"], st["cache_dir"],
                     st["version"], vs, summary)
    return rel, vs, summary


def lint_paths(paths=DEFAULT_PATHS, root: str = REPO_ROOT,
               rules: list[Rule] | None = None, *,
               project_rules=None, jobs: int | None = None,
               cache: bool = True, cache_dir: str | None = None,
               stats: dict | None = None) -> list[Violation]:
    """Two-pass repo lint. Per-file rules run (or replay from cache) per
    file — in parallel when ``jobs`` > 1; the interprocedural pass runs
    over the per-file summaries every time (repo-global by nature).

    ``project_rules``: None = all interprocedural rules (pass-2
    concurrency + pass-3 dataflow); [] = skip both passes.
    ``stats`` (optional dict) is filled with files/hits/misses counts.
    """
    from .concurrency import (check_project, default_project_rules,
                              in_scope)

    rules = rules if rules is not None else _all_rules()
    if project_rules is None:
        project_rules = list(default_project_rules())
    cache_dir = cache_dir or CACHE_DIR
    version = ruleset_version(root)
    rules_sig = ",".join(sorted(r.id for r in rules))

    files: list[tuple[str, str]] = []   # (relpath, source)
    out: list[Violation] = []
    for ap in iter_py_files(paths, root):
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        try:
            with open(ap, encoding="utf-8") as f:
                files.append((rel, f.read()))
        except OSError as e:
            out.append(Violation(rule="io-error", path=rel, line=1, col=0,
                                 message=str(e), snippet=""))

    summaries: dict[str, dict | None] = {}
    sources = dict(files)
    hits = 0
    misses: list[tuple[str, str, str]] = []  # (rel, source, content_key)
    for rel, source in files:
        key = hashlib.sha1(source.encode("utf-8")).hexdigest()
        got = (_cache_load(rel, key, rules_sig, cache_dir, version)
               if cache else None)
        if got is not None:
            vs, summary = got
            out.extend(vs)
            summaries[rel] = summary
            hits += 1
        else:
            misses.append((rel, source, key))

    def _scan(item):
        rel, source, key = item
        from .project import extract_summary

        vs = lint_source(source, relpath=rel, rules=rules)
        summary = extract_summary(rel, source) if in_scope(rel) else None
        if cache:
            _cache_store(rel, key, rules_sig, cache_dir, version, vs,
                         summary)
        return rel, vs, summary

    if misses:
        results = None
        # the scan is GIL-bound pure-python AST work, so real parallelism
        # needs PROCESSES (a thread pool measures SLOWER than serial);
        # spawn context keeps the children free of the parent's jax/XLA
        # state. Only stock rules survive reconstruction in a child —
        # custom rule instances fall back to the serial path.
        from . import rules as rules_mod

        known = {cls.id for cls in rules_mod.ALL_RULES}
        if jobs and jobs > 1 and len(misses) > 1 \
                and all(r.id in known for r in rules):
            try:
                import multiprocessing
                from concurrent.futures import ProcessPoolExecutor

                ctx = multiprocessing.get_context("spawn")
                init_args = (sorted(r.id for r in rules), cache, cache_dir,
                             version, rules_sig)
                with ProcessPoolExecutor(max_workers=jobs, mp_context=ctx,
                                         initializer=_worker_init,
                                         initargs=init_args) as ex:
                    results = list(ex.map(_worker_scan, misses,
                                          chunksize=max(
                                              len(misses) // (jobs * 4),
                                              1)))
            except (OSError, ValueError, ImportError):
                results = None   # sandboxed env without fork/sem: serial
        if results is None:
            results = [_scan(m) for m in misses]
        for rel, vs, summary in results:
            out.extend(vs)
            summaries[rel] = summary

    if project_rules:
        out.extend(check_project(summaries, sources, rules=project_rules))

    if stats is not None:
        stats.update(files=len(files), hits=hits, misses=len(misses))
    return sorted(out, key=lambda v: (v.path, v.line, v.col, v.rule))


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str = BASELINE_PATH) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("entries", [])


def baseline_keys(entries: list[dict]) -> set[tuple]:
    return {(e["rule"], e["path"], e["snippet"]) for e in entries}


def apply_baseline(violations: list[Violation],
                   entries: list[dict]) -> list[Violation]:
    keys = baseline_keys(entries)
    return [v for v in violations if v.key() not in keys]


def write_baseline(violations: list[Violation], path: str = BASELINE_PATH,
                   old_entries: list[dict] | None = None) -> None:
    """Deterministic regeneration: sorted by (path, line, rule), repo-
    relative paths, one entry per distinct (rule, path, snippet), reasons
    carried over from the previous baseline when the key survives."""
    reasons = {(e["rule"], e["path"], e["snippet"]): e.get("reason", "")
               for e in (old_entries if old_entries is not None
                         else load_baseline(path))}
    seen: set[tuple] = set()
    entries = []
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.rule)):
        if v.key() in seen:
            continue
        seen.add(v.key())
        entries.append({"rule": v.rule, "path": v.path, "line": v.line,
                        "snippet": v.snippet,
                        "reason": reasons.get(v.key(), "baselined")})
    payload = {"version": 1,
               "comment": ("pre-existing violations grandfathered out of the "
                           "gate; match on (rule, path, snippet) so line "
                           "drift does not resurrect them. Regenerate with "
                           "python -m tools.graftlint --baseline-update"),
               "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# machine-readable output (--format sarif|github)
# ---------------------------------------------------------------------------
def _rule_catalog() -> list:
    from . import rules as rules_mod
    from .concurrency import default_project_rules

    return [cls() for cls in
            tuple(rules_mod.ALL_RULES) + default_project_rules()]


def render_sarif(violations: list[Violation]) -> str:
    """SARIF 2.1.0 — one run, one result per violation, rules carried in
    the tool component so CI annotators can show the doc line."""
    docs = {r.id: r.doc for r in _rule_catalog()}
    rule_ids = sorted({v.rule for v in violations})
    sarif = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "tools/graftlint/",
                "rules": [{"id": rid,
                           "shortDescription": {"text": docs.get(rid, rid)}}
                          for rid in rule_ids],
            }},
            "results": [{
                "ruleId": v.rule,
                "level": "error" if v.severity == "error" else "warning",
                "message": {"text": v.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    # endLine/endColumn make GitHub underline the flagged
                    # expression instead of a zero-width caret at its
                    # start (endColumn is 1-based exclusive, so the
                    # 0-based-exclusive ast end_col_offset maps via +1)
                    "region": {**{"startLine": v.line,
                                  "startColumn": v.col + 1,
                                  "snippet": {"text": v.snippet}},
                               **({"endLine": max(v.line_end, v.line),
                                   "endColumn": v.col_end + 1}
                                  if v.col_end > 0 else {})},
                }}],
            } for v in violations],
        }],
    }
    return json.dumps(sarif, indent=1, sort_keys=True)


def render_github(violations: list[Violation]) -> str:
    """GitHub Actions workflow commands — one ::error per violation, so a
    CI run annotates the diff inline with no extra tooling."""
    lines = []
    for v in violations:
        msg = v.message.replace("%", "%25").replace("\n", "%0A")
        span = (f",endLine={max(v.line_end, v.line)},"
                f"endColumn={v.col_end + 1}" if v.col_end > 0 else "")
        lines.append(f"::error file={v.path},line={v.line},"
                     f"col={v.col + 1}{span},title=graftlint "
                     f"{v.rule}::{msg}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    import argparse

    from . import rules as rules_mod
    from .concurrency import default_project_rules

    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-native static analysis for the JAX and "
                    "concurrency hazard classes this codebase keeps "
                    "re-fixing")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: %(default)s)")
    ap.add_argument("--fix", action="store_true",
                    help="auto-rewrite the mechanical rules (shard_map "
                         "imports -> parallel.mesh, registered knob env "
                         "reads -> knobs.raw)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="regenerate baseline.json from the current tree "
                         "(deterministic: sorted, path-relative)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined violations too")
    ap.add_argument("--select",
                    help="comma list of rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallel workers for the per-file scan")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and don't write .graftlint_cache/")
    ap.add_argument("--format", choices=("text", "sarif", "github"),
                    default="text",
                    help="finding output format (default: %(default)s)")
    args = ap.parse_args(argv)

    rules = [cls() for cls in rules_mod.ALL_RULES]
    proj_rules = [cls() for cls in default_project_rules()]
    if args.list_rules:
        for r in rules + proj_rules:
            print(f"{r.id:24} [{r.severity}] {r.doc}")
        return 0
    if args.select:
        wanted = {s.strip() for s in args.select.split(",")}
        known = {r.id for r in rules} | {r.id for r in proj_rules}
        unknown = wanted - known
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
        proj_rules = [r for r in proj_rules if r.id in wanted]

    if args.fix:
        from . import fixes

        changed = fixes.fix_paths(args.paths, root=REPO_ROOT)
        for path in changed:
            print(f"fixed: {path}")

    if args.baseline_update and (args.select
                                 or args.paths != list(DEFAULT_PATHS)):
        # a narrowed run sees only a slice of the violations; writing the
        # baseline from it would silently drop every other entry (and its
        # hand-written reason)
        print("--baseline-update requires a full default-scope run "
              "(no --select, no explicit paths)", file=sys.stderr)
        return 2

    violations = lint_paths(args.paths, rules=rules,
                            project_rules=proj_rules, jobs=args.jobs,
                            cache=not args.no_cache)
    if args.baseline_update:
        write_baseline(violations, path=args.baseline)
        print(f"baseline: {len(violations)} entr"
              f"{'y' if len(violations) == 1 else 'ies'} -> {args.baseline}")
        return 0
    if not args.no_baseline:
        violations = apply_baseline(violations, load_baseline(args.baseline))
    if args.format == "sarif":
        print(render_sarif(violations))
    elif args.format == "github":
        if violations:
            print(render_github(violations))
    else:
        for v in violations:
            print(v.render())
        n = len(violations)
        print(f"graftlint: {n} violation{'s' if n != 1 else ''} "
              f"({'FAIL' if n else 'ok'})")
    return 1 if violations else 0
