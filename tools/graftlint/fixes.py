"""graftlint --fix — conservative auto-rewrites for the mechanical rules.

Two fixers, both AST-located and text-applied (edits sorted bottom-up so
offsets stay valid):

- direct-shard-map: `from jax.experimental.shard_map import shard_map` /
  `from jax import shard_map` becomes
  `from h2o_tpu.parallel.mesh import shard_map`, and dotted call sites
  (`jax.experimental.shard_map.shard_map(...)`) collapse to the imported
  name. Only the plain spellings are rewritten — anything aliased or
  star-imported is left for a human (the lint still flags it).
- knob reads: `os.environ.get("H2O_TPU_X", d)` / `os.getenv("H2O_TPU_X")`
  of a REGISTERED knob becomes `knobs.raw("H2O_TPU_X", d)` — behavior-
  identical (raw string or the given default), with
  `from h2o_tpu.utils import knobs` inserted after the last top-level
  import if missing. Unregistered knobs are NOT fixable mechanically (the
  fix is a registry declaration); they keep failing the lint.
"""

from __future__ import annotations

import ast
import os
import re

from .core import REPO_ROOT, collect_aliases, dotted_name, iter_py_files, \
    normalize
from .rules import KNOBS_PATH, MESH_PATH, registered_knobs

#: (start_line, start_col, end_line, end_col, replacement) — 1-based lines
Edit = tuple[int, int, int, int, str]

MESH_IMPORT = "from h2o_tpu.parallel.mesh import shard_map"
KNOBS_IMPORT = "from h2o_tpu.utils import knobs"


def _node_span(node: ast.AST) -> tuple[int, int, int, int]:
    return (node.lineno, node.col_offset, node.end_lineno,
            node.end_col_offset)


def _apply_edits(source: str, edits: list[Edit]) -> str:
    lines = source.splitlines(keepends=True)
    for sl, sc, el, ec, rep in sorted(edits, reverse=True):
        head = lines[sl - 1][:sc]
        tail = lines[el - 1][ec:]
        lines[sl - 1:el] = [head + rep + tail]
    return "".join(lines)


def _insert_import(source: str, tree: ast.Module, import_line: str) -> str:
    """Insert ``import_line`` after the LEADING prelude — docstring,
    __future__ and the contiguous top import block — never later: a module
    may execute rewritten code between import groups (tests/conftest.py
    reads env knobs mid-prelude), so inserting after the last import in the
    file could place the import below its first use."""
    if any(isinstance(n, (ast.Import, ast.ImportFrom))
           and source.splitlines()[n.lineno - 1].strip() == import_line
           for n in tree.body):
        return source
    prelude_end = 0
    for n in tree.body:
        is_doc = (n is tree.body[0] and isinstance(n, ast.Expr)
                  and isinstance(n.value, ast.Constant)
                  and isinstance(n.value.value, str))
        if not (is_doc or isinstance(n, (ast.Import, ast.ImportFrom))):
            break
        prelude_end = n.end_lineno or n.lineno
    lines = source.splitlines(keepends=True)
    nl = "\n"
    insert = import_line + nl
    if prelude_end == 0:
        # no docstring/imports — still respect a shebang (line 1) and a
        # PEP 263 coding cookie (lines 1-2): both are position-sensitive
        while (prelude_end < min(len(lines), 2)
               and (lines[prelude_end].startswith("#!")
                    or re.match(r"#.*coding[:=]", lines[prelude_end]))):
            prelude_end += 1
        if prelude_end == 0:
            return insert + nl + source
    return "".join(lines[:prelude_end] + [nl, insert]
                   + lines[prelude_end:])


def fix_shard_map_imports(source: str, relpath: str) -> str:
    if relpath.replace(os.sep, "/") == MESH_PATH:
        return source
    tree = ast.parse(source)
    edits: list[Edit] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            plain = [a for a in node.names
                     if a.name == "shard_map" and a.asname is None]
            # NOT the `from jax.experimental import shard_map` module form:
            # its call sites spell `shard_map.shard_map(...)`, which a
            # function import would break — the lint flags it for a human
            if (mod in ("jax.experimental.shard_map", "jax") and plain
                    and len(node.names) == 1):
                edits.append((*_node_span(node), MESH_IMPORT))
        elif isinstance(node, ast.Attribute):
            dn = dotted_name(node)
            if dn in ("jax.experimental.shard_map.shard_map",
                      "jax.shard_map"):
                edits.append((*_node_span(node), "shard_map"))
    if not edits:
        return source
    fixed = _apply_edits(source, edits)
    # an attribute rewrite needs the shim import in scope
    if any(rep == "shard_map" for *_, rep in edits):
        fixed = _insert_import(fixed, ast.parse(fixed), MESH_IMPORT)
    return fixed


def fix_knob_reads(source: str, relpath: str,
                   registry: set[str] | None = None) -> str:
    rel = relpath.replace(os.sep, "/")
    if rel == KNOBS_PATH or rel.startswith("h2o_tpu/utils/"):
        # knobs.py itself and its neighbors (optargs reads env generically)
        return source
    registry = registered_knobs() if registry is None else registry
    tree = ast.parse(source)
    aliases = collect_aliases(tree)
    edits: list[Edit] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = normalize(dotted_name(node.func), aliases)
        if fn not in ("os.environ.get", "os.getenv", "environ.get"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if not name.startswith("H2O_TPU_") or name not in registry:
            continue
        if node.keywords:        # os.environ.get(key, default=...) — rare
            continue
        edits.append((*_node_span(node.func), "knobs.raw"))
    if not edits:
        return source
    fixed = _apply_edits(source, edits)
    return _insert_import(fixed, ast.parse(fixed), KNOBS_IMPORT)


def fix_source(source: str, relpath: str,
               registry: set[str] | None = None) -> str:
    source = fix_shard_map_imports(source, relpath)
    source = fix_knob_reads(source, relpath, registry=registry)
    return source


def fix_paths(paths, root: str = REPO_ROOT) -> list[str]:
    """Apply all fixers in place; returns repo-relative paths changed."""
    registry = registered_knobs(root)
    changed = []
    for ap in iter_py_files(paths, root):
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        with open(ap, encoding="utf-8") as f:
            src = f.read()
        try:
            fixed = fix_source(src, rel, registry=registry)
        except SyntaxError:
            continue
        if fixed != src:
            with open(ap, "w", encoding="utf-8") as f:
                f.write(fixed)
            changed.append(rel)
    return changed
