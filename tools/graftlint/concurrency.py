"""graftlint pass 2 — interprocedural concurrency rules (14-17).

The platform became a genuinely concurrent system (serving batcher/shadow
workers, REST handler threads, background jobs, the Cleaner reservation
ledger) and the per-file rules 1-13 cannot see the bug class that hurts
next: a field raced between a request thread and a worker, a lock-order
inversion between two subsystems, a device sync on the batch path while a
lock is held. These rules run on the repo-wide :class:`ProjectModel`
(tools/graftlint/project.py — symbol table, call graph, thread-entry map)
instead of a single file's AST:

14. unguarded-shared-field — a ``self.*`` field written outside
    ``__init__`` and touched from ≥2 thread roots (spawned workers, REST
    handler threads, the public entry surface) must be accessed under ONE
    consistent inferred guard. Guarded-by inference reads ``with
    self._lock:`` scopes and propagates through one level of private
    helper methods (a ``*_locked`` helper only ever called under the lock
    inherits it).
15. lock-order-cycle — the static lock-acquisition graph (lock A held
    while B is acquired → edge A→B, propagated through the call graph)
    must be acyclic; any cycle is a deadlock candidate. The runtime twin
    (`h2o_tpu/utils/sanitizer.py`) raises on *observed* inversions; this
    rule flags *possible* ones.
16. blocking-under-lock — no ``time.sleep`` / ``block_until_ready`` /
    ``device_get`` / HTTP / thread-or-job join / ``Event.wait`` while
    holding a lock (waiting on the HELD condition is exempt — that
    releases it). One level of interprocedural lookthrough: calling a
    helper that blocks counts. This is the serving-p99 killer class.
17. unjoined-thread — a ``threading.Thread``/``Timer`` created with no
    join on any path (``self.X`` spawn with no ``self.X.join()`` anywhere
    in the class; a local spawn with no join in the function;
    fire-and-forget anonymous threads) leaks workers past shutdown.

All four are deliberately under-approximate where resolution is
ambiguous (no edge beats a wrong edge); everything they DO flag is either
fixed or baselined with a written reason — the gate ships at 0
non-baselined violations, the rules 1-13 discipline.

Scope: everything scanned except the test tree — on the default scan set
that is ``h2o_tpu/`` + ``bench.py``, the host-side driver whose
race-freedom the MapReduce determinism story depends on. Tests spawn
threads with their own lifecycles and stay per-file-linted only.
"""

from __future__ import annotations

import os

from .core import Violation, suppression_table
from .project import ProjectModel, extract_summary

#: call-graph BFS bound for closure queries (lock closures); the repo's
#: real chains are < 10 deep, this is a runaway guard, not a tuning knob
_CLOSURE_DEPTH = 12


def in_scope(relpath: str) -> bool:
    """Interprocedural scope: everything scanned EXCEPT the test tree —
    tests spawn threads with their own lifecycles and stay per-file-
    linted only. On the default scan set this means h2o_tpu/ + bench.py;
    an explicit out-of-tree path gets the full analysis too."""
    p = relpath.replace(os.sep, "/")
    return not (p.startswith("tests/") or "/tests/" in p)


class ProjectRule:
    """One interprocedural rule: ``check(model) -> [(path, line, msg)]``."""

    id: str = ""
    severity: str = "error"
    doc: str = ""

    def check(self, model: ProjectModel) -> list[tuple]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# rule 14 — unguarded-shared-field
# ---------------------------------------------------------------------------
class UnguardedSharedField(ProjectRule):
    id = "unguarded-shared-field"
    doc = ("self.* field written from >=2 thread roots without one "
           "consistent inferred guard (with self._lock scopes, incl. one "
           "level of private helper methods)")

    def _class_functions(self, model: ProjectModel, path: str,
                         cls: str) -> dict:
        return {k: fn for k, fn in model.functions.items()
                if fn["path"] == path and fn.get("cls") == cls}

    @staticmethod
    def _helper_guards(fns: dict) -> dict:
        """{fnkey: extra guard set} for private helpers whose every
        intra-class call site holds a common lock (one inference level)."""
        call_guards: dict[str, list] = {}
        by_name = {}
        for key, fn in fns.items():
            # only direct methods (not nested closures) are addressable
            # through self.m() — qual "Class.m" has exactly one dot
            if fn["qual"].count(".") == 1:
                by_name[fn["name"]] = key
        for key, fn in fns.items():
            for kind, name, _recv, guards, _line in fn.get("calls", []):
                if kind == "self" and name in by_name:
                    call_guards.setdefault(by_name[name],
                                           []).append(set(guards))
        out: dict[str, set] = {}
        for key, sites in call_guards.items():
            fn = fns[key]
            if fn.get("public"):
                continue  # externally callable — call sites don't cover it
            common = set.intersection(*sites) if sites else set()
            if common:
                out[key] = common
        return out

    def check(self, model: ProjectModel) -> list[tuple]:
        out: list[tuple] = []
        reachable = model.thread_reachable()
        for (path, cls), crec in sorted(model.classes.items()):
            if not in_scope(path):
                continue
            fns = self._class_functions(model, path, cls)
            if not fns:
                continue
            extra = self._helper_guards(fns)
            # field -> [(root label, mode, guards, line, fnkey)]
            fields: dict[str, list] = {}
            for key, fn in sorted(fns.items()):
                if "__init__" in fn["qual"]:
                    continue  # construction happens-before publication
                root = reachable.get(key, "entry")
                bonus = extra.get(key, set())
                for fld, guards, line in fn.get("writes", []):
                    fields.setdefault(fld, []).append(
                        (root, "w", set(guards) | bonus, line, key))
                for fld, guards, line in fn.get("reads", []):
                    fields.setdefault(fld, []).append(
                        (root, "r", set(guards) | bonus, line, key))
            for fld in sorted(fields):
                accesses = fields[fld]
                if fld.isupper():
                    continue  # module-constant convention
                roots = {a[0] for a in accesses}
                writes = [a for a in accesses if a[1] == "w"]
                if len(roots) < 2 or not writes:
                    continue
                common = set.intersection(*(a[2] for a in accesses))
                if common:
                    continue  # one consistent guard covers every access
                # inferred guard = the most used lock across accesses
                counts: dict[str, int] = {}
                for a in accesses:
                    for gkey in a[2]:
                        counts[gkey] = counts.get(gkey, 0) + 1
                if counts:
                    inferred = sorted(counts.items(),
                                      key=lambda kv: (-kv[1], kv[0]))[0][0]
                    offenders = [a for a in accesses
                                 if inferred not in a[2]]
                    detail = (f"this access misses the inferred guard "
                              f"'{inferred}' the other accesses hold")
                else:
                    offenders = writes
                    detail = "no access holds any lock"
                anchor = sorted(offenders,
                                key=lambda a: (a[1] != "w", a[3]))[0]
                other = sorted(roots)[:3]
                out.append((path, anchor[3],
                            f"field '{cls}.{fld}' is shared between "
                            f"thread roots ({'; '.join(other)}) and "
                            f"written outside __init__, but {detail} — "
                            f"guard every access with one lock (or "
                            f"baseline with a reason if the race is "
                            f"benign)"))
        return out


# ---------------------------------------------------------------------------
# rule 15 — lock-order-cycle
# ---------------------------------------------------------------------------
class LockOrderCycle(ProjectRule):
    id = "lock-order-cycle"
    doc = ("cycle in the static lock-acquisition graph (lock A held while "
           "acquiring B, across the call graph) — a deadlock candidate")

    def _closure_locks(self, model: ProjectModel, start: str,
                       memo: dict) -> set:
        """Lock ids acquired anywhere in ``start``'s call closure."""
        if start in memo:
            return memo[start]
        memo[start] = set()  # cycle guard
        acc: set = set()
        seen = {start}
        frontier = [start]
        depth = 0
        while frontier and depth < _CLOSURE_DEPTH:
            nxt = []
            for key in frontier:
                fn = model.functions.get(key)
                if fn is None:
                    continue
                for tok, _held, _line, _blocking in fn.get("acquires", []):
                    lid = model.lock_id(key, tok)
                    if lid is not None:
                        acc.add(lid)
                for kind, name, recv, _g, _line in fn.get("calls", []):
                    tgt = model.resolve_call(key, kind, name, recv)
                    if tgt is not None and tgt not in seen:
                        seen.add(tgt)
                        nxt.append(tgt)
            frontier = nxt
            depth += 1
        memo[start] = acc
        return acc

    def check(self, model: ProjectModel) -> list[tuple]:
        edges: dict[tuple, tuple] = {}  # (a, b) -> (path, line)

        def note(a: str, b: str, path: str, line: int) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (path, line)

        memo: dict = {}
        for key, fn in sorted(model.functions.items()):
            if not in_scope(fn["path"]):
                continue
            for tok, held, line, _blocking in fn.get("acquires", []):
                b = model.lock_id(key, tok)
                if b is None:
                    continue
                for h in held:
                    a = model.lock_id(key, h)
                    if a is not None:
                        note(a, b, fn["path"], line)
            for kind, name, recv, guards, line in fn.get("calls", []):
                if not guards:
                    continue
                tgt = model.resolve_call(key, kind, name, recv)
                if tgt is None:
                    continue
                for b in self._closure_locks(model, tgt, memo):
                    for h in guards:
                        a = model.lock_id(key, h)
                        if a is not None:
                            note(a, b, fn["path"], line)

        # cycle detection over the edge set (iterative DFS per SCC seed)
        graph: dict[str, list] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        sccs = _sccs(graph)
        out: list[tuple] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp = sorted(comp)
            cyc_edges = sorted((a, b) for (a, b) in edges
                               if a in comp and b in comp)
            path, line = edges[cyc_edges[0]]
            sites = "; ".join(
                f"{a.split('::')[-1]}->{b.split('::')[-1]} at "
                f"{edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in cyc_edges[:4])
            out.append((path, line,
                        f"lock-order cycle between "
                        f"{', '.join(c.split('::')[-1] for c in comp)} — "
                        f"a deadlock candidate ({sites}); pick one global "
                        f"order or drop a lock from one path"))
        return out


def _sccs(graph: dict) -> list:
    """Tarjan strongly-connected components, iterative (deterministic:
    nodes visited in sorted order)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, []))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, [])))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


# ---------------------------------------------------------------------------
# rule 16 — blocking-under-lock
# ---------------------------------------------------------------------------
#: dotted spellings that block the calling thread
_BLOCKING_DOTTED = {
    "time.sleep", "jax.device_get", "jax.block_until_ready",
    "urllib.request.urlopen", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "select.select",
}
#: attribute spellings that block regardless of receiver
_BLOCKING_ATTRS = {"block_until_ready", "device_get", "communicate",
                   "getresponse", "urlopen", "serve_forever", "result"}


class BlockingUnderLock(ProjectRule):
    id = "blocking-under-lock"
    doc = ("blocking call (sleep/block_until_ready/device_get/HTTP/"
           "thread-or-job join/Event.wait) while holding a lock — the "
           "serving p99 killer; waiting on the HELD condition is exempt")

    def _direct_blocking(self, fn: dict, thread_attrs: set) -> list:
        """[(line, what, guards)] of blocking calls in one function."""
        out = []
        for kind, name, recv, guards, line in fn.get("calls", []):
            what = None
            if kind == "dotted" and (name in _BLOCKING_DOTTED
                                     or name.endswith(".sleep")
                                     and name.startswith("time")):
                what = name
            elif kind in ("attr", "dotted"):
                last = name.rsplit(".", 1)[-1]
                if last in _BLOCKING_ATTRS:
                    what = last
                elif last == "wait" and recv is not None \
                        and recv not in guards:
                    # Event/Future .wait under a lock blocks WITH the lock;
                    # cv.wait on a held condition releases it — exempt
                    what = f"{recv}.wait"
                elif last == "join" and recv in thread_attrs:
                    what = f"{recv}.join"
            elif kind == "name" and name in ("sleep", "urlopen"):
                what = name
            if what is not None:
                out.append((line, what, guards))
        return out

    def check(self, model: ProjectModel) -> list[tuple]:
        # class -> attrs that store spawned threads (join targets)
        thread_attrs: dict[tuple, set] = {}
        for key, fn in model.functions.items():
            for _ref, store, _line, kind in fn.get("spawns", []):
                if kind == "thread" and store and store.startswith("self."):
                    thread_attrs.setdefault(
                        (fn["path"], fn.get("cls")), set()).add(store)
        out: list[tuple] = []
        direct: dict[str, list] = {}
        for key, fn in model.functions.items():
            tattrs = thread_attrs.get((fn["path"], fn.get("cls")), set())
            direct[key] = self._direct_blocking(fn, tattrs)
        for key, fn in sorted(model.functions.items()):
            if not in_scope(fn["path"]):
                continue
            for line, what, guards in direct[key]:
                if guards:
                    held = ", ".join(sorted(set(guards)))
                    out.append((fn["path"], line,
                                f"blocking call {what} while holding "
                                f"{held} — every other thread contending "
                                f"on that lock stalls behind it; move the "
                                f"wait outside the lock"))
            # one level of lookthrough: a call under a lock to a helper
            # that blocks directly
            for kind, name, recv, guards, line in fn.get("calls", []):
                if not guards:
                    continue
                tgt = model.resolve_call(key, kind, name, recv)
                if tgt is None or not direct.get(tgt):
                    continue
                whats = sorted({w for _l, w, _g in direct[tgt]})
                held = ", ".join(sorted(set(guards)))
                out.append((fn["path"], line,
                            f"call to {name}() while holding {held} — the "
                            f"callee blocks ({', '.join(whats[:3])}); "
                            f"move the call outside the lock"))
        return out


# ---------------------------------------------------------------------------
# rule 17 — unjoined-thread
# ---------------------------------------------------------------------------
class UnjoinedThread(ProjectRule):
    id = "unjoined-thread"
    doc = ("threading.Thread/Timer created with no join on the shutdown "
           "path (self.X spawn with no self.X.join() in the class; local "
           "spawn with no join in the function; fire-and-forget)")

    def check(self, model: ProjectModel) -> list[tuple]:
        # joins per (path, cls) and per function
        class_joins: dict[tuple, set] = {}
        for key, fn in model.functions.items():
            cj = class_joins.setdefault((fn["path"], fn.get("cls")), set())
            cj.update(j for j in fn.get("joins", [])
                      if j.startswith("self."))
        out: list[tuple] = []
        for key, fn in sorted(model.functions.items()):
            if not in_scope(fn["path"]):
                continue
            local_joins = {j for j in fn.get("joins", [])
                           if j.startswith("local:")}
            for _ref, store, line, kind in fn.get("spawns", []):
                if kind != "thread":
                    continue
                if store and store.startswith("self."):
                    if store in class_joins.get(
                            (fn["path"], fn.get("cls")), set()):
                        continue
                    what = (f"thread stored on {store} is never joined "
                            f"anywhere in class {fn.get('cls')}")
                elif store and store.startswith("local:"):
                    if store in local_joins:
                        continue
                    what = (f"thread '{store[6:]}' is never joined in "
                            f"{fn['qual']}")
                else:
                    what = "fire-and-forget thread (no handle kept)"
                out.append((fn["path"], line,
                            f"{what} — shutdown cannot drain it; keep the "
                            f"handle and join on the stop path (or "
                            f"baseline with a reason if detaching is the "
                            f"design)"))
        return out


PROJECT_RULES = (UnguardedSharedField, LockOrderCycle, BlockingUnderLock,
                 UnjoinedThread)


def default_project_rules() -> tuple:
    """Pass 2 (concurrency) + pass 3 (dataflow) rule classes — the full
    interprocedural rule set a default run executes. Lazy import: dataflow
    imports from this module."""
    from .dataflow import DATAFLOW_RULES

    return tuple(PROJECT_RULES) + tuple(DATAFLOW_RULES)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------
def check_project(summaries: dict, sources: dict,
                  rules=None) -> list[Violation]:
    """Run the interprocedural rules over pre-extracted summaries.

    ``sources`` maps relpath -> source text (for snippets/suppressions);
    files without a summary (syntax errors, out of scope) are skipped.
    Rules yield ``(path, line, msg)`` or ``(path, line, msg, col,
    col_end)`` — the dataflow rules carry column spans so SARIF/GitHub
    annotations underline the exact expression.
    """
    model = ProjectModel({p: s for p, s in summaries.items()
                          if s is not None and in_scope(p)})
    out: list[Violation] = []
    suppress_cache: dict[str, dict] = {}
    lines_cache: dict[str, list] = {}
    for cls in (rules if rules is not None else default_project_rules()):
        rule = cls() if isinstance(cls, type) else cls
        for finding in rule.check(model):
            path, line, message = finding[0], finding[1], finding[2]
            col = finding[3] if len(finding) > 3 else 0
            col_end = finding[4] if len(finding) > 4 else 0
            src = sources.get(path)
            if src is None:
                snippet, suppressed = "", False
            else:
                if path not in lines_cache:
                    lines_cache[path] = src.splitlines()
                    suppress_cache[path] = suppression_table(src)
                lines = lines_cache[path]
                snippet = (lines[line - 1].strip()
                           if 1 <= line <= len(lines) else "")
                tab = suppress_cache[path]
                ids = tab.get(line, "absent")
                suppressed = (ids is None
                              or (ids != "absent" and rule.id in ids))
            if suppressed:
                continue
            out.append(Violation(rule=rule.id, path=path, line=line,
                                 col=col, message=message, snippet=snippet,
                                 severity=rule.severity, col_end=col_end))
    return sorted(out, key=lambda v: (v.path, v.line, v.col, v.rule))


def lint_project(sources: dict, rules=None) -> list[Violation]:
    """Fixture/test entry point: interprocedural lint over in-memory
    sources ({relpath: source}). Suppressions apply; baseline does not."""
    sources = {p.replace(os.sep, "/"): s for p, s in sources.items()}
    summaries = {p: extract_summary(p, s) for p, s in sources.items()}
    return check_project(summaries, sources, rules=rules)
