"""graftlint rule catalog — 8 JAX hazard classes this repo has actually hit.

Each rule cites the incident that motivated it (PR numbers refer to
CHANGES.md entries):

1. direct-shard-map      — PR 1: the seed suite was 100% import-broken on
   jax 0.4.x because `shard_map` moved between jax versions; the
   version-bridged shim in `h2o_tpu/parallel/mesh.py` is the ONLY sanctioned
   import point (ROADMAP "jax version skew" item).
2. pspec-concat          — PR 1: on jax 0.4.x `PartitionSpec.__add__`
   returns a plain tuple, which shard_map rejects; specs must be built in
   one constructor call (`parallel/mrtask.py` carries the war story).
3. narrow-int-accumulate — PR 2: the binned histogram scan summed int8
   codes; reductions over sub-int32 operands overflow silently on device.
4. untracked-resident    — device arrays parked on objects bypass the HBM
   Cleaner ledger (`backend/memory.py`) and silently distort every
   budget-driven planner; residency must be Cleaner-tracked.
5. timing-without-sync   — jax dispatch is async: a wall-clock delta over
   un-synced device work measures dispatch, not compute (the bench JSONL
   sidecar numbers exist to be trusted).
6. host-sync-in-trace    — `.item()`/`float()`/`np.asarray` on traced
   values fail under jit, or worse: silently bake a trace-time constant in.
7. nondeterminism-in-trace — `np.random`/`time.time()` inside traced code
   executes ONCE at trace time; every later call replays the frozen value.
8. unregistered-knob     — literal `H2O_TPU_*` env reads must be declared
   in `h2o_tpu/utils/knobs.py` so the knob surface stays documented and
   greppable (the OptArgs discipline, enforced).
9. unregistered-failpoint — PR 5: literal failpoint site names must be
   declared in `h2o_tpu/utils/failpoints.py`; an undeclared site is a
   fault drill nobody can arm (the knobs discipline, applied to fault
   injection).
10. swallowed-retryable  — PR 5: `except Exception: pass` around an
   instrumented (failpoint) site swallows injected faults — and with them
   the real transient failures the drill stands in for; transient errors
   route through `utils/retry.py` or unwind typed.
11. unregistered-metric  — PR 6: literal metric names emitted through
   `utils/telemetry.py` accessors must be declared in its registry; an
   undeclared name raises at runtime (KeyError, the knobs contract) — this
   rule catches it before a hot path does.
12. direct-pallas-call   — PR 9: `h2o_tpu/backend/kernels/` is the ONLY
   sanctioned `pl.pallas_call` site (the direct-shard-map shape, applied
   to kernels): a Pallas kernel grown elsewhere dodges the XLA-oracle
   bit-parity contract, the interpret-mode routing off-TPU, and the
   `H2O_TPU_HIST_KERNEL` backend switch.
13. direct-device-put    — PR 10 (multi-chip sharded frames): mesh-sharded
   `jax.device_put` calls belong to `parallel/mesh.py`'s put_* helpers or
   the frame layer (`frame/vec.py`, `frame/chunks.py`). Placement policy —
   what is row-sharded, what replicates per chip — decides per-chip HBM
   and collective layouts; a stray `device_put(x, NamedSharding(...))` in
   a builder silently re-lays frame data outside the one reviewable
   policy (the GSPMD merge mis-partition hid exactly there).
18. use-after-donate     — PR 12 (async pipelined training): a variable
   passed through a `donate_argnums` position of a jitted callable hands
   its buffer to the runtime — reading it afterwards dies at dispatch
   time with "array has been deleted" (or silently copies on backends
   without donation). The pipelined GBM chunk loop donates the carried
   margin across dispatches; this rule pins the rebind-or-copy
   discipline everywhere the pattern spreads. (Rules 14-17 are the
   interprocedural concurrency pass in `concurrency.py`.)
19. unscoped-profiler-capture — PR 13 (fleet observability): jax.profiler
   `start_trace`/`stop_trace`/`trace` outside `utils/telemetry.py` /
   `utils/fleetobs.py`. Captures must ride the span-scoped API
   (`telemetry.device_profile`/`capture`): it mirrors the live span
   stack into TraceAnnotations (XLA ops nest under `train.gbm.chunk` in
   Perfetto), enforces one session per process, and guarantees
   stop_trace on every exit path — an ad-hoc start_trace leaks a
   session the next capture then cannot open.
24. thread-without-trace-context — PR 15 (causal observability):
   contextvars do not cross `threading.Thread(target=...)` starts or
   executor submits, so a worker thread spawned in a span-bearing module
   (one that imports `utils/telemetry`) mints ORPHAN trace ids for every
   span it opens — the MicroBatcher and shadow-scorer spans silently
   fell out of their requests' traces for two PRs before anyone noticed.
   Thread targets and executor submissions in such modules must route
   through `telemetry.carry_context(fn)` (capture-at-wrap semantics);
   threads that legitimately own no causality (the REST acceptor, the
   teardown thread, the watchdog) carry an inline suppression with the
   reason. (Rules 20-23 are the dataflow pass in `dataflow.py`.)
"""

from __future__ import annotations

import ast
import os

from .core import (REPO_ROOT, FileContext, Rule, Violation, dotted_name,
                   function_scopes, normalize, scope_statements)

#: the one sanctioned shard_map definition site
MESH_PATH = "h2o_tpu/parallel/mesh.py"
#: the one sanctioned pallas_call site (the kernels layer)
KERNELS_PATH = "h2o_tpu/backend/kernels/"
KNOBS_PATH = "h2o_tpu/utils/knobs.py"
FAILPOINTS_PATH = "h2o_tpu/utils/failpoints.py"
TELEMETRY_PATH = "h2o_tpu/utils/telemetry.py"

_NARROW_INTS = {"int8", "int16", "uint8", "uint16"}
_WIDE_TYPES = {"int32", "int64", "uint32", "uint64",
               "float32", "float64", "bfloat16", "float16"}


def _norm_func(node: ast.Call, ctx: FileContext) -> str | None:
    return normalize(dotted_name(node.func), ctx.aliases)


class DirectShardMap(Rule):
    id = "direct-shard-map"
    doc = ("shard_map imported/used outside h2o_tpu/parallel/mesh.py — "
           "route through the version-bridged shim (jax 0.4.x skew)")

    def check(self, tree, ctx):
        if ctx.relpath == MESH_PATH:
            return []
        out = []
        spans: list[tuple] = []  # flagged attribute-chain spans
        msg = ("direct jax shard_map use — import it from "
               "h2o_tpu.parallel.mesh (the version-bridged shim; "
               "ROADMAP 'jax version skew')")
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if (mod == "jax.experimental.shard_map"
                        or (mod in ("jax", "jax.experimental")
                            and "shard_map" in names)):
                    out.append(self.violation(ctx, node, msg))
            elif isinstance(node, ast.Import):
                if any(a.name.startswith("jax.experimental.shard_map")
                       for a in node.names):
                    out.append(self.violation(ctx, node, msg))
            elif isinstance(node, ast.Attribute):
                dn = normalize(dotted_name(node), ctx.aliases)
                if dn and (dn == "jax.shard_map"
                           or "experimental.shard_map" in dn):
                    # outermost matching attribute only: ast.walk visits
                    # outer before inner, so skip a chain whose span is
                    # CONTAINED in an already-flagged one (two disjoint
                    # uses on one line both report)
                    lo = (node.lineno, node.col_offset)
                    hi = (node.end_lineno, node.end_col_offset)
                    if not any(s0 <= lo and hi <= s1 for s0, s1 in spans):
                        spans.append((lo, hi))
                        out.append(self.violation(ctx, node, msg))
        return out


class DirectPallasCall(Rule):
    id = "direct-pallas-call"
    doc = ("pallas imported/used outside h2o_tpu/backend/kernels/ — the "
           "kernels layer is the only sanctioned pl.pallas_call site "
           "(XLA-oracle parity + interpret routing)")

    def check(self, tree, ctx):
        if ctx.relpath.startswith(KERNELS_PATH):
            return []
        out = []
        spans: list[tuple] = []
        msg = ("direct pallas use — kernels live in h2o_tpu/backend/"
               "kernels/ (the sanctioned pl.pallas_call site: XLA-oracle "
               "bit parity, interpret-mode routing off-TPU, and the "
               "H2O_TPU_HIST_KERNEL backend switch)")
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if (mod.startswith("jax.experimental.pallas")
                        or (mod == "jax.experimental" and "pallas" in names)):
                    out.append(self.violation(ctx, node, msg))
            elif isinstance(node, ast.Import):
                if any(a.name.startswith("jax.experimental.pallas")
                       for a in node.names):
                    out.append(self.violation(ctx, node, msg))
            elif isinstance(node, ast.Attribute):
                dn = normalize(dotted_name(node), ctx.aliases)
                if dn and ("experimental.pallas" in dn
                           or dn.endswith(".pallas_call")):
                    # outermost matching attribute chain only (the
                    # direct-shard-map span discipline)
                    lo = (node.lineno, node.col_offset)
                    hi = (node.end_lineno, node.end_col_offset)
                    if not any(s0 <= lo and hi <= s1 for s0, s1 in spans):
                        spans.append((lo, hi))
                        out.append(self.violation(ctx, node, msg))
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)):
                # bare `pallas_call(...)` resolved through its import alias
                # (an unimported local name of the same spelling is not
                # pallas and stays clean)
                dn = normalize(dotted_name(node.func), ctx.aliases)
                if dn and "experimental.pallas" in dn:
                    out.append(self.violation(ctx, node, msg))
        return out


#: the sanctioned mesh-sharded placement sites — the mesh helpers
#: themselves plus the frame layer's (re)hydration paths
PLACEMENT_PATHS = (MESH_PATH, "h2o_tpu/frame/vec.py",
                   "h2o_tpu/frame/chunks.py")


class DirectDevicePut(Rule):
    id = "direct-device-put"
    doc = ("mesh-sharded jax.device_put outside parallel/mesh.py / the "
           "frame layer — route frame-data placement through the mesh "
           "put_* helpers so sharding policy stays in one place")

    #: constructors whose result is a mesh sharding (placing with a bare
    #: Device object — serving replica pinning — is NOT flagged: that is
    #: device selection, not frame-data partitioning)
    _SHARDING_CTORS = {"NamedSharding", "PositionalSharding",
                       "row_sharding", "replicated"}

    def _is_sharding(self, node, shard_vars) -> bool:
        if isinstance(node, ast.Name):
            return node.id in shard_vars
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            return bool(dn) and dn.split(".")[-1] in self._SHARDING_CTORS
        return False

    def check(self, tree, ctx):
        if ctx.relpath in PLACEMENT_PATHS:
            return []
        out = []
        for scope in function_scopes(tree):
            shard_vars: set[str] = set()
            stmts = sorted(scope_statements(scope),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0)))
            for node in stmts:
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and self._is_sharding(node.value, shard_vars)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            shard_vars.add(t.id)
                if not isinstance(node, ast.Call):
                    continue
                fn = _norm_func(node, ctx)
                if not fn or not fn.endswith("device_put"):
                    continue
                target = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg in ("device", "sharding"):
                        target = kw.value
                if target is not None and self._is_sharding(target,
                                                            shard_vars):
                    out.append(self.violation(
                        ctx, node,
                        "mesh-sharded device_put outside the sanctioned "
                        "placement sites — use parallel/mesh.py's "
                        "put_row_sharded/put_replicated/put_sharded (or "
                        "the frame layer) so per-chip placement policy "
                        "stays reviewable in one place"))
        return out


class PSpecConcat(Rule):
    id = "pspec-concat"
    doc = ("PartitionSpec combined via '+' — jax 0.4.x __add__ returns a "
           "raw tuple; build the spec in one constructor call")

    _CTORS = {"PartitionSpec", "P"}

    def _is_spec(self, node, spec_vars) -> bool:
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            return bool(dn) and dn.split(".")[-1] in self._CTORS
        if isinstance(node, ast.Name):
            return node.id in spec_vars
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            # nested concat chains: (P(a) + P(b)) + P(c)
            return (self._is_spec(node.left, spec_vars)
                    or self._is_spec(node.right, spec_vars))
        return False

    def check(self, tree, ctx):
        out = []
        for scope in function_scopes(tree):
            spec_vars: set[str] = set()
            spans: list[tuple] = []  # flagged BinOp spans (outermost wins)
            stmts = sorted(scope_statements(scope),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0)))
            for node in stmts:
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and self._is_spec(node.value, spec_vars)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            spec_vars.add(t.id)
                if (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Add)
                        and (self._is_spec(node.left, spec_vars)
                             or self._is_spec(node.right, spec_vars))):
                    # one violation per chain: the sorted order visits the
                    # OUTERMOST BinOp of `(P(a)+P(b))+P(c)` first, and the
                    # inner adds live inside its span
                    lo = (node.lineno, node.col_offset)
                    hi = (node.end_lineno, node.end_col_offset)
                    if any(s0 <= lo and hi <= s1 for s0, s1 in spans):
                        continue
                    spans.append((lo, hi))
                    out.append(self.violation(
                        ctx, node,
                        "PartitionSpec '+' concatenation — on jax 0.4.x "
                        "P.__add__ returns a plain tuple (shard_map rejects "
                        "it); pass all axes to one PartitionSpec(...) call"))
        return out


class NarrowIntAccumulate(Rule):
    id = "narrow-int-accumulate"
    doc = ("jnp.sum/segment_sum/psum over int8/int16 operands without an "
           "explicit int32 upcast — silent on-device overflow")

    _ACCUM = {"jnp.sum", "lax.psum", "jnp.cumsum", "lax.psum_scatter"}
    _ACCUM_SUFFIX = ("segment_sum",)

    def _dtype_of(self, node) -> str | None:
        """Name of the dtype an expression mentions ('int8', 'float32'...),
        for the handful of spellings the repo uses."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        dn = dotted_name(node)
        if dn:
            return dn.split(".")[-1]
        return None

    def _is_narrow_expr(self, node, narrow_vars) -> bool:
        if isinstance(node, ast.Name):
            return node.id in narrow_vars
        if isinstance(node, ast.Call):
            # x.astype(jnp.int8) / x.astype("int16")
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("astype", "view") and node.args):
                return self._dtype_of(node.args[0]) in _NARROW_INTS
            # jnp.zeros(shape, jnp.int8) / jnp.asarray(x, dtype=jnp.int8)
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return self._dtype_of(kw.value) in _NARROW_INTS
            if len(node.args) >= 2:
                if self._dtype_of(node.args[-1]) in _NARROW_INTS:
                    return True
        if isinstance(node, ast.BinOp):
            return (self._is_narrow_expr(node.left, narrow_vars)
                    or self._is_narrow_expr(node.right, narrow_vars))
        return False

    def _has_upcast(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "dtype":
                return self._dtype_of(kw.value) in _WIDE_TYPES
        if call.args:
            a = call.args[0]
            if (isinstance(a, ast.Call)
                    and isinstance(a.func, ast.Attribute)
                    and a.func.attr == "astype" and a.args
                    and self._dtype_of(a.args[0]) in _WIDE_TYPES):
                return True
        return False

    def check(self, tree, ctx):
        out = []
        for scope in function_scopes(tree):
            narrow_vars: set[str] = set()
            stmts = sorted(scope_statements(scope),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0)))
            # pass 1: variables bound to narrow-int expressions
            for node in stmts:
                if isinstance(node, ast.Assign):
                    if self._is_narrow_expr(node.value, narrow_vars):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                narrow_vars.add(t.id)
                    else:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                narrow_vars.discard(t.id)
            # pass 2: accumulations over narrow operands
            for node in stmts:
                if not isinstance(node, ast.Call):
                    continue
                fn = _norm_func(node, ctx)
                is_accum = (fn in self._ACCUM
                            or (fn or "").endswith(self._ACCUM_SUFFIX))
                # narrow_arr.sum() method form
                if (not is_accum and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("sum", "cumsum")
                        and self._is_narrow_expr(node.func.value,
                                                 narrow_vars)):
                    is_accum = True
                    arg = node.func.value
                else:
                    arg = node.args[0] if node.args else None
                if not is_accum or arg is None:
                    continue
                if (self._is_narrow_expr(arg, narrow_vars)
                        and not self._has_upcast(node)):
                    out.append(self.violation(
                        ctx, node,
                        "accumulation over a sub-int32 operand — pass "
                        "dtype=jnp.int32 or .astype(jnp.int32) first "
                        "(PR 2 binned-histogram overflow class)"))
        return out


class UntrackedResident(Rule):
    id = "untracked-resident"
    doc = ("device array assigned to self.* in frame/ or models/ classes "
           "with no Cleaner.track/_put_sharding registration — silent HBM "
           "ledger leak vs backend/memory.py")

    _SCOPES = ("h2o_tpu/frame/", "h2o_tpu/models/")
    _TRACKED_BASES = {"Vec", "CodedVec", "BinnedView", "Keyed", "Frame"}

    def _device_expr(self, node, ctx) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = _norm_func(node, ctx)
        if fn is None:
            return False
        return (fn.startswith("jnp.")
                or fn in ("jax.device_put", "jax.make_array_from_callback"))

    def check(self, tree, ctx):
        if not ctx.relpath.startswith(self._SCOPES):
            return []
        out = []
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            base_names = {dn.split(".")[-1] for dn in
                          (dotted_name(b) for b in cls.bases) if dn}
            if base_names & self._TRACKED_BASES:
                continue  # Vec/Keyed subclasses register via __init__
            registered = False
            for node in ast.walk(cls):
                if (isinstance(node, ast.Attribute)
                        and node.attr in ("track", "_put_sharding")):
                    registered = True
                    break
            if registered:
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                if not self._device_expr(node.value, ctx):
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.append(self.violation(
                            ctx, node,
                            f"device array parked on self.{t.attr} with no "
                            f"Cleaner.track/_put_sharding registration — "
                            f"invisible to the HBM ledger "
                            f"(backend/memory.py)"))
        return out


class TimingWithoutSync(Rule):
    id = "timing-without-sync"
    doc = ("wall-clock delta spans jax dispatch with no block_until_ready/"
           "device_get — measures dispatch, not compute")

    _CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
               "perf_counter", "monotonic"}
    #: repo entry points that dispatch device work behind a host call.
    #: train_model is NOT here: ModelBuilder.train drains the model's
    #: device arrays before returning (model_base.py), so timing around it
    #: is honest by contract — and that contract is itself lint-protected,
    #: because model_base.run's own timed window classifies build_impl as
    #: dispatch and needs the block_until_ready to stay clean.
    _DISPATCH_METHODS = {"build_impl"}
    _SYNC_NAMES = {"block_until_ready", "device_get", "to_numpy", "item"}
    _SYNC_FULL = {"np.asarray", "np.array"}
    _BENIGN_JAX = {"jax.devices", "jax.local_devices", "jax.device_count",
                   "jax.default_backend", "jax.process_index",
                   "jax.process_count", "jax.clear_caches",
                   "jax.config.update", "jax.debug.print"}

    def _is_clock(self, node, ctx) -> bool:
        return (isinstance(node, ast.Call)
                and _norm_func(node, ctx) in self._CLOCKS)

    def _classify(self, node: ast.Call, ctx) -> str | None:
        """'sync' | 'dispatch' | None for a call node."""
        fn = _norm_func(node, ctx)
        last = (fn or (node.func.attr if isinstance(node.func, ast.Attribute)
                       else "")).split(".")[-1]
        if fn in self._SYNC_FULL or last in self._SYNC_NAMES:
            return "sync"
        if last in self._DISPATCH_METHODS:
            return "dispatch"
        if fn is None:
            return None
        if fn in self._BENIGN_JAX or fn in self._CLOCKS:
            return None
        if (fn.startswith(("jnp.", "lax.", "jax."))
                or fn in ("jnp", "lax")):
            return "dispatch"
        return None

    def check(self, tree, ctx):
        out = []
        for scope in function_scopes(tree):
            starts: dict[str, list[int]] = {}   # timer var -> assign lines
            deltas: list[tuple[int, ast.BinOp, str]] = []
            calls: list[tuple[int, str]] = []
            for node in scope_statements(scope):
                if (isinstance(node, ast.Assign)
                        and self._is_clock(node.value, ctx)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            starts.setdefault(t.id, []).append(node.lineno)
                elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                                ast.Sub):
                    if (self._is_clock(node.left, ctx)
                            and isinstance(node.right, ast.Name)):
                        deltas.append((node.lineno, node, node.right.id))
                elif isinstance(node, ast.Call):
                    kind = self._classify(node, ctx)
                    if kind:
                        calls.append((node.lineno, kind))
            for dline, dnode, tvar in deltas:
                cands = [ln for ln in starts.get(tvar, []) if ln < dline]
                if not cands:
                    continue
                t0 = max(cands)  # the LATEST restart before this read
                window = [(ln, k) for ln, k in calls if t0 < ln <= dline]
                if (any(k == "dispatch" for _, k in window)
                        and not any(k == "sync" for _, k in window)):
                    out.append(self.violation(
                        ctx, dnode,
                        f"timed window (line {t0}..{dline}) spans jax "
                        f"dispatch with no block_until_ready/device_get — "
                        f"the delta measures dispatch, not compute"))
        return out


class HostSyncInTrace(Rule):
    id = "host-sync-in-trace"
    doc = (".item()/float()/bool()/np.asarray on traced values inside "
           "jit/scan/shard_map bodies — fails under jit or bakes in a "
           "trace-time constant")

    _CASTS = {"float", "bool"}
    _FULL = {"np.asarray", "np.array", "jax.device_get"}

    @staticmethod
    def _static_arg(node) -> bool:
        """Arguments that are trace-static: literals, or anything derived
        from .shape/.ndim/.size/.dtype/len() (python ints at trace time)."""
        if isinstance(node, ast.Constant):
            return True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                    "shape", "ndim", "size", "dtype", "itemsize"):
                return True
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id == "len"):
                return True
        return False

    def check(self, tree, ctx):
        out = []
        seen: set[int] = set()
        for fn in ctx.traced:
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or id(node) in seen:
                        continue
                    seen.add(id(node))
                    msg = None
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in self._CASTS
                            and node.args
                            and not self._static_arg(node.args[0])):
                        msg = (f"{node.func.id}() on a traced value inside "
                               f"a jit/scan/shard_map body")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr == "item"):
                        msg = ".item() on a traced value inside a traced body"
                    elif _norm_func(node, ctx) in self._FULL:
                        msg = (f"{_norm_func(node, ctx)} inside a traced "
                               f"body forces a host sync at trace time")
                    if msg:
                        out.append(self.violation(
                            ctx, node, msg + " — fails under jit or "
                            "freezes a trace-time constant"))
        return out


class NondeterminismInTrace(Rule):
    id = "nondeterminism-in-trace"
    doc = ("np.random/time.time reachable from traced code — the value "
           "freezes at trace time and silently replays")

    _PREFIXES = ("np.random.", "random.")
    _FULL = {"time.time", "time.perf_counter", "time.monotonic",
             "time.time_ns", "uuid.uuid4", "np.random"}

    def check(self, tree, ctx):
        out = []
        seen: set[int] = set()
        for fn in ctx.traced:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                f = _norm_func(node, ctx)
                if f and (f in self._FULL
                          or f.startswith(self._PREFIXES)):
                    out.append(self.violation(
                        ctx, node,
                        f"{f}() inside a traced body executes ONCE at "
                        f"trace time — use jax.random with a threaded key "
                        f"(or hoist the host value out of the trace)"))
        return out


def registered_knobs(root: str = REPO_ROOT) -> set[str]:
    """Knob names declared in h2o_tpu/utils/knobs.py — read via AST so the
    linter never imports the (jax-heavy) package it lints."""
    path = os.path.join(root, KNOBS_PATH)
    names: set[str] = set()
    if not os.path.exists(path):
        return names
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("H2O_TPU_")
                and dotted_name(node.func) in ("_knob", "Knob")):
            names.add(node.args[0].value)
    return names


class UnregisteredKnob(Rule):
    id = "unregistered-knob"
    doc = ("literal H2O_TPU_* env read not declared in the "
           "h2o_tpu/utils/knobs.py registry")

    _GETTERS = {"os.environ.get", "os.getenv", "environ.get"}

    def __init__(self, registry: set[str] | None = None):
        self._registry = registry

    @property
    def registry(self) -> set[str]:
        if self._registry is None:
            self._registry = registered_knobs()
        return self._registry

    def _flag(self, ctx, node, name):
        return self.violation(
            ctx, node,
            f"env knob {name!r} is not declared in h2o_tpu/utils/knobs.py "
            f"— register it (name, default, docstring) so the knob surface "
            f"stays documented")

    def check(self, tree, ctx):
        if ctx.relpath == KNOBS_PATH:
            return []
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = normalize(dotted_name(node.func), ctx.aliases)
                if (fn in self._GETTERS and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    name = node.args[0].value
                    if (name.startswith("H2O_TPU_")
                            and name not in self.registry):
                        out.append(self._flag(ctx, node, name))
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)):
                base = normalize(dotted_name(node.value), ctx.aliases)
                if base in ("os.environ", "environ"):
                    sl = node.slice
                    if (isinstance(sl, ast.Constant)
                            and isinstance(sl.value, str)
                            and sl.value.startswith("H2O_TPU_")
                            and sl.value not in self.registry):
                        out.append(self._flag(ctx, node, sl.value))
        return out


def registered_failpoints(root: str = REPO_ROOT) -> set[str]:
    """Failpoint sites declared in h2o_tpu/utils/failpoints.py — AST-parsed
    like the knob registry, so the linter never imports the package."""
    path = os.path.join(root, FAILPOINTS_PATH)
    names: set[str] = set()
    if not os.path.exists(path):
        return names
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and dotted_name(node.func) in ("_failpoint", "Failpoint")):
            names.add(node.args[0].value)
    return names


class UnregisteredFailpoint(Rule):
    id = "unregistered-failpoint"
    doc = ("literal failpoint site name not declared in the "
           "h2o_tpu/utils/failpoints.py registry")

    #: accessor attributes whose literal first argument is a site name
    _ACCESSORS = ("hit", "arm", "disarm", "is_armed", "hits")

    def __init__(self, registry: set[str] | None = None):
        self._registry = registry

    @property
    def registry(self) -> set[str]:
        if self._registry is None:
            self._registry = registered_failpoints()
        return self._registry

    def check(self, tree, ctx):
        if ctx.relpath == FAILPOINTS_PATH:
            return []
        out = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            fn = _norm_func(node, ctx)
            if fn is None or not any(
                    fn.endswith(f"failpoints.{acc}")
                    for acc in self._ACCESSORS):
                continue
            name = node.args[0].value
            if name not in self.registry:
                out.append(self.violation(
                    ctx, node,
                    f"failpoint {name!r} is not declared in "
                    f"h2o_tpu/utils/failpoints.py — register it (name, "
                    f"docstring) so every fault drill stays armable and "
                    f"documented"))
        return out


def _contains_failpoint_hit(stmts, ctx) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                fn = _norm_func(node, ctx)
                if fn is not None and fn.endswith("failpoints.hit"):
                    return True
    return False


class SwallowedRetryable(Rule):
    id = "swallowed-retryable"
    doc = ("broad except-and-ignore around an instrumented (failpoint) "
           "site — injected faults, and the real transient failures they "
           "stand in for, must not vanish silently")

    _BROAD = {"Exception", "BaseException"}

    def _is_broad_expr(self, t) -> bool:
        """Exception/BaseException as a bare Name, dotted builtins.*, or any
        member of a tuple handler — `except (Exception,):` swallows exactly
        as much as `except Exception:`."""
        if isinstance(t, ast.Name):
            return t.id in self._BROAD
        if isinstance(t, ast.Attribute):
            return (t.attr in self._BROAD
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "builtins")
        if isinstance(t, ast.Tuple):
            return any(self._is_broad_expr(el) for el in t.elts)
        return False

    def check(self, tree, ctx):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Try):
                continue
            if not _contains_failpoint_hit(node.body, ctx):
                continue
            for handler in node.handlers:
                t = handler.type
                broad = t is None or self._is_broad_expr(t)
                if not broad:
                    continue
                body = [s for s in handler.body
                        if not (isinstance(s, ast.Expr)
                                and isinstance(s.value, ast.Constant))]
                ignores = all(isinstance(s, (ast.Pass, ast.Continue))
                              for s in body)
                if ignores:
                    out.append(self.violation(
                        ctx, handler,
                        "broad except silently ignores failures from an "
                        "instrumented site — a failpoint drill (and the "
                        "real transient fault it models) would vanish "
                        "here; retry through utils/retry.py or let the "
                        "typed error unwind"))
        return out


def registered_metrics(root: str = REPO_ROOT) -> set[str]:
    """Metric names declared in h2o_tpu/utils/telemetry.py — AST-parsed
    like the knob/failpoint registries, so the linter never imports the
    (jax-adjacent) package."""
    path = os.path.join(root, TELEMETRY_PATH)
    names: set[str] = set()
    if not os.path.exists(path):
        return names
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and dotted_name(node.func) in ("_counter", "_gauge",
                                               "_histogram", "Metric")):
            names.add(node.args[0].value)
    return names


class UnregisteredMetric(Rule):
    id = "unregistered-metric"
    doc = ("literal metric name emitted through utils/telemetry.py "
           "accessors but not declared in its registry")

    #: accessors whose literal FIRST argument is a metric name
    _ACCESSORS = ("inc", "observe", "set_gauge", "value")
    #: span/lap constructors carry the metric as a `metric=` keyword
    _METRIC_KW = ("span", "lap", "Lap")

    def __init__(self, registry: set[str] | None = None):
        self._registry = registry

    @property
    def registry(self) -> set[str]:
        if self._registry is None:
            self._registry = registered_metrics()
        return self._registry

    def _flag(self, ctx, node, name):
        return self.violation(
            ctx, node,
            f"metric {name!r} is not declared in "
            f"h2o_tpu/utils/telemetry.py — register it (name, kind, "
            f"docstring) so /3/Metrics stays documented and the emit "
            f"cannot KeyError a hot path at runtime")

    def check(self, tree, ctx):
        if ctx.relpath == TELEMETRY_PATH:
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _norm_func(node, ctx)
            if fn is None:
                continue
            if (any(fn.endswith(f"telemetry.{acc}")
                    for acc in self._ACCESSORS)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                if name not in self.registry:
                    out.append(self._flag(ctx, node, name))
            elif any(fn.endswith(f"telemetry.{c}")
                     for c in self._METRIC_KW):
                for kw in node.keywords:
                    if (kw.arg == "metric"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)
                            and kw.value.value not in self.registry):
                        out.append(self._flag(ctx, node, kw.value.value))
        return out


class UseAfterDonate(Rule):
    id = "use-after-donate"
    doc = ("variable read after being passed through a donate_argnums "
           "position of the same jitted callable — the donated buffer is "
           "gone at dispatch; rebind the result or copy first")

    @staticmethod
    def _donated_positions(call: ast.Call):
        """frozenset of donated positions from a jax.jit call's
        donate_argnums (int or tuple/list of int literals), else None."""
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return frozenset([v.value])
            if isinstance(v, (ast.Tuple, ast.List)):
                vals = frozenset(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int))
                if vals:
                    return vals
        return None

    #: factory callables known to return a donating trainer: callee name
    #: -> donated positions of the RETURNED callable when the factory is
    #: called with donate=True (engine.make_train_fn donates the carried
    #: margin, argument 3). This per-file rule still can't see the chunk
    #: loop's `*step_args` dispatch — the pass-3 `donate-across-calls`
    #: rule (tools/graftlint/dataflow.py) resolves donating factories
    #: through the call graph and star-dispatch through tuple packs, so
    #: that flow IS lint-visible now; this list keeps the cheap per-file
    #: rule useful for same-file reads (tests included — pass 3 scopes
    #: to h2o_tpu/ + bench.py).
    _DONATING_FACTORIES = {"make_train_fn": frozenset([3])}

    def _binding_positions(self, value: ast.expr, ctx) -> frozenset | None:
        """Donated positions for a callable bound from ``value``: a
        literal `jax.jit(..., donate_argnums=...)` call, a known donating
        factory called with donate=True, or an IfExp with either arm one
        of those (conservative: donation assumed when any arm donates)."""
        if isinstance(value, ast.IfExp):
            return (self._binding_positions(value.body, ctx)
                    or self._binding_positions(value.orelse, ctx))
        if not isinstance(value, ast.Call):
            return None
        fn = _norm_func(value, ctx)
        if fn and fn.endswith("jax.jit"):
            return self._donated_positions(value)
        tail = (fn or "").rsplit(".", 1)[-1]
        if tail in self._DONATING_FACTORIES:
            for kw in value.keywords:
                if (kw.arg == "donate"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    return self._DONATING_FACTORIES[tail]
        return None

    def check(self, tree, ctx):
        # pass 1, file-wide: bindings of donating callables — literal
        # `name = jax.jit(..., donate_argnums=...)`, donating factories,
        # and IfExp-wrapped variants
        donating: dict[str, frozenset] = {}
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                pos = self._binding_positions(node.value, ctx)
                if pos:
                    donating[node.targets[0].id] = pos
        if not donating:
            return []
        out = []
        msg = ("read of {name!r} after it was donated to {fn!r} "
               "(donate_argnums) — the buffer is deleted at dispatch; "
               "rebind the call's result or copy before dispatching")
        for scope in function_scopes(tree):
            # line-ordered event stream: loads check against the donated
            # set, call-site donations mark at the call's END line (args
            # may span lines), stores/dels clear at their statement's END
            # line (RHS evaluates before targets bind — `f, o = fn(x, f)`
            # donates the old f and rebinds, which is the clean idiom)
            events = []   # (line, phase, name, node, fn)
            for node in scope_statements(scope):
                if isinstance(node, ast.stmt):
                    end = node.end_lineno or node.lineno
                    for sub in ast.walk(node):
                        if (isinstance(sub, ast.Name)
                                and isinstance(sub.ctx, (ast.Store,
                                                         ast.Del))):
                            events.append((end, 2, sub.id, None, None))
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in donating):
                    end = node.end_lineno or node.lineno
                    for p in donating[node.func.id]:
                        if (p < len(node.args)
                                and isinstance(node.args[p], ast.Name)):
                            events.append((end, 1, node.args[p].id, None,
                                           node.func.id))
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)):
                    events.append((node.lineno, 0, node.id, node, None))
            donated: dict[str, str] = {}   # name -> donating fn
            for _line, phase, name, node, fn in sorted(
                    events, key=lambda e: (e[0], e[1])):
                if phase == 0 and name in donated:
                    out.append(self.violation(
                        ctx, node, msg.format(name=name,
                                              fn=donated[name])))
                    del donated[name]   # one report per donation
                elif phase == 1:
                    donated[name] = fn
                elif phase == 2:
                    donated.pop(name, None)
        return out


#: the sanctioned jax.profiler capture sites — telemetry owns the
#: span-scoped capture API (annotations + guaranteed stop_trace),
#: fleetobs the fleet-coordinated captures
PROFILER_PATHS = ("h2o_tpu/utils/telemetry.py", "h2o_tpu/utils/fleetobs.py")


class UnscopedProfilerCapture(Rule):
    id = "unscoped-profiler-capture"
    doc = ("jax.profiler start_trace/stop_trace/trace outside "
           "utils/telemetry.py / utils/fleetobs.py — captures must ride "
           "the span-scoped API (telemetry.device_profile / capture) so "
           "TraceAnnotations nest XLA ops under the span names and "
           "stop_trace is guaranteed on every exit path")

    _CAPTURE_NAMES = ("start_trace", "stop_trace", "trace",
                      "start_server")

    def _is_capture(self, dn: str) -> bool:
        if not dn or "profiler" not in dn:
            return False
        tail = dn.rsplit(".", 1)[-1]
        return tail in self._CAPTURE_NAMES

    def check(self, tree, ctx):
        if ctx.relpath in PROFILER_PATHS:
            return []
        out = []
        spans: list[tuple] = []
        msg = ("unscoped jax.profiler capture — route it through "
               "utils/telemetry.py's device_profile()/capture() (the "
               "span-scoped API: annotations nest XLA ops under telemetry "
               "span names, one session per process is enforced, and "
               "stop_trace cannot be leaked on an error path)")
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if (mod.endswith("jax.profiler") or mod == "jax.profiler") \
                        and names & set(self._CAPTURE_NAMES):
                    out.append(self.violation(ctx, node, msg))
            elif isinstance(node, ast.Attribute):
                dn = normalize(dotted_name(node), ctx.aliases)
                if dn and self._is_capture(dn):
                    # outermost matching attribute chain only (the
                    # direct-pallas-call span discipline)
                    lo = (node.lineno, node.col_offset)
                    hi = (node.end_lineno, node.end_col_offset)
                    if not any(s0 <= lo and hi <= s1 for s0, s1 in spans):
                        spans.append((lo, hi))
                        out.append(self.violation(ctx, node, msg))
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)):
                # bare `start_trace(...)` resolved through an import alias
                dn = normalize(dotted_name(node.func), ctx.aliases)
                if dn and self._is_capture(dn) and "profiler" in dn:
                    out.append(self.violation(ctx, node, msg))
        return out


class ThreadWithoutTraceContext(Rule):
    id = "thread-without-trace-context"
    doc = ("threading.Thread(target=...) / executor submit in a module "
           "that imports utils/telemetry must wrap the callable in "
           "telemetry.carry_context(...) — contextvars do not cross "
           "thread starts, so the worker's spans orphan into fresh trace "
           "ids (the MicroBatcher/shadow-scorer hole PR 15 closed)")

    _MSG = ("worker thread/submit in a span-bearing module without "
            "telemetry.carry_context() — the thread's spans will mint "
            "orphan trace ids instead of nesting under the submitter's "
            "(wrap the target: Thread(target=telemetry.carry_context(fn)) "
            "/ ex.submit(telemetry.carry_context(fn), ...); threads that "
            "own no causality suppress inline with the reason)")

    @staticmethod
    def _bears_spans(tree) -> bool:
        """Module imports utils/telemetry (module- or function-level) —
        the modules whose spans can orphan."""
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if any(a.name == "telemetry" for a in node.names) or \
                        (node.module or "").endswith("telemetry"):
                    return True
            elif isinstance(node, ast.Import):
                if any(a.name.endswith(".telemetry") for a in node.names):
                    return True
        return False

    @staticmethod
    def _is_carried(node) -> bool:
        """True when the callable expression routes through
        carry_context (telemetry.carry_context(fn) or an alias of it)."""
        if not isinstance(node, ast.Call):
            return False
        dn = dotted_name(node.func)
        return bool(dn) and dn.rsplit(".", 1)[-1] == "carry_context"

    def _executor_vars(self, tree, ctx) -> set:
        """Names bound to ThreadPoolExecutor/ProcessPoolExecutor
        instances — via assignment or `with ...() as ex:`."""
        out = set()

        def _note(target, value):
            if isinstance(target, ast.Name) and isinstance(value, ast.Call):
                dn = normalize(dotted_name(value.func), ctx.aliases) or ""
                if dn.rsplit(".", 1)[-1] in ("ThreadPoolExecutor",
                                             "ProcessPoolExecutor"):
                    out.add(target.id)

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _note(t, node.value)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        _note(item.optional_vars, item.context_expr)
        return out

    def check(self, tree, ctx):
        if not ctx.relpath.startswith("h2o_tpu/"):
            return []           # the span-bearing tree; tests/tools spawn
        if ctx.relpath == TELEMETRY_PATH:
            return []           # carry_context's own home
        if not self._bears_spans(tree):
            return []
        out = []
        executors = self._executor_vars(tree, ctx)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dn = normalize(dotted_name(node.func), ctx.aliases) or ""
            if dn == "threading.Thread" or dn.endswith(".threading.Thread"):
                # positional signature is Thread(group, target, ...) —
                # args[0] is GROUP, the callable is args[1]
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"),
                              node.args[1] if len(node.args) > 1 else None)
                if target is not None and not self._is_carried(target):
                    out.append(self.violation(ctx, node, self._MSG))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("submit", "map") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in executors:
                fn = node.args[0] if node.args else None
                if fn is not None and not self._is_carried(fn):
                    out.append(self.violation(ctx, node, self._MSG))
        return out


ALL_RULES = (DirectShardMap, DirectPallasCall, DirectDevicePut, PSpecConcat,
             NarrowIntAccumulate, UntrackedResident, TimingWithoutSync,
             HostSyncInTrace, NondeterminismInTrace, UnregisteredKnob,
             UnregisteredFailpoint, SwallowedRetryable, UnregisteredMetric,
             UseAfterDonate, UnscopedProfilerCapture,
             ThreadWithoutTraceContext)
