"""graftlint pass 3 — array-provenance dataflow rules (20-23).

The failure classes that actually kill TPU performance are not syntax-
local: an implicit device→host transfer hides two calls away from the
chunk loop, a row-sharded array meets a replicated one in a builder that
never mentions sharding, a jitted callable quietly gets a fresh cache key
every iteration, a donated buffer crosses a function boundary before it
is read. These rules run over the repo-wide :class:`ProjectModel`
(pass 1's symbol table + call graph) extended with the per-function
**provenance event stream** (`project.py` — where values acquire a
device placement or a host domain, which ops touch them, which calls
carry them):

20. host-transfer-in-hot-path — ``np.*`` / ``float()`` / ``.item()`` /
    implicit-bool applied to a device-provenance value inside the HOT
    sections (train chunk loop, MRTask dispatch, serving score path,
    Cleaner sweep — the roots, closed over the call graph). Unlike the
    per-file ``host-sync-in-trace`` rule this is interprocedural: a
    device value handed to a helper that host-syncs its parameter flags
    at the call site. The sanctioned spelling is an EXPLICIT
    ``jax.device_get`` at a declared sync point (which the runtime twin
    ``H2O_TPU_SANITIZE=transfers`` permits and implicit conversions
    violate) — that is why ``device_get`` is never flagged.
21. mixed-sharding-combine — a row-sharded and a replicated provenance
    meeting in one host-level op: GSPMD silently inserts a resharding
    collective. Inside ``shard_map``-traced bodies the mix is the
    sanctioned shape (per-shard compute + replicated metadata) and is
    exempt; so is an operand that was explicitly re-placed (a
    ``mesh.put_*`` call is not a bare ref, so it never records).
22. recompile-hazard — a jit/AOT cache key that cannot stabilize:
    compiled-callable construction (``jax.jit`` / ``programs.tracked`` /
    ``.lower(...)``) inside a loop; a per-iteration Python value in a
    ``static_argnums`` position; a non-hashable container literal as a
    static argument; a per-iteration comprehension argument (pytree
    length churn) to a jit-bound callable. The runtime twin
    (``H2O_TPU_SANITIZE=recompiles``) raises on the compile this rule
    predicts.
23. donate-across-calls — rule 18 made interprocedural. Donating
    callables are discovered through the call graph (a factory returning
    ``jax.jit(..., donate_argnums=...)`` marks every binding of its
    result, across modules), donation propagates through tuple packs and
    ``f(*args)`` star-dispatch, and a function that forwards a parameter
    into a donated position is itself summarized as donating that
    parameter — so the GBM chunk loop's ``train_fn(*step_args)`` margin
    dispatch is lint-visible, not just test-pinned.

All four stay deliberately under-approximate (an unknown provenance or
an unresolved call produces no finding, never a wrong one); everything
they DO flag is either fixed or baselined with a written reason — the
empty-baseline discipline of rules 1-19.
"""

from __future__ import annotations

from .concurrency import ProjectRule, in_scope
from .project import ProjectModel

#: provenance tags that mean "device-resident"
_DEVICE_TAGS = {"row", "rep", "dev"}
#: bounded recursion for interprocedural summaries (real chains are short)
_DEPTH = 6

#: the hot roots — (path suffix, function name, section label). Functions
#: reachable from a root over the call graph inherit its label. These are
#: the sections the runtime twin (`H2O_TPU_SANITIZE=transfers`) scopes a
#: jax transfer guard over; the rule and the guard must name the same
#: code or the static and dynamic stories diverge.
HOT_ROOTS = (
    ("parallel/mrtask.py", "_dispatch", "MRTask dispatch"),
    ("models/gbm.py", "build_impl", "train chunk loop"),
    ("serving/batcher.py", "_run", "serving batch worker"),
    ("serving/scorer.py", "score", "serving score path"),
    ("serving/scorer.py", "_score_bucket", "serving score path"),
    ("serving/runtime.py", "score", "serving score path"),
    ("backend/memory.py", "maybe_sweep", "Cleaner sweep"),
    ("backend/memory.py", "emergency_sweep", "Cleaner sweep"),
)


class ProvInfo:
    """Shared pass-3 analysis over one ProjectModel, computed lazily and
    memoized per query (the rules below all read it). Attached to the
    model object so the four rules share one instance per run."""

    def __init__(self, model: ProjectModel):
        self.model = model
        self._ret_tag: dict = {}
        self._returns_don: dict = {}
        self._ret_pack: dict = {}
        self._donates_params: dict = {}
        self._host_param: dict = {}
        self.hot = self._hot_closure()

    @classmethod
    def of(cls, model: ProjectModel) -> "ProvInfo":
        info = getattr(model, "_prov_info", None)
        if info is None:
            info = cls(model)
            model._prov_info = info
        return info

    # -- basics ----------------------------------------------------------------
    def events(self, key: str) -> list:
        fn = self.model.functions.get(key)
        return (fn or {}).get("prov") or []

    def params(self, key: str) -> list:
        fn = self.model.functions.get(key)
        return (fn or {}).get("params") or []

    def _resolve(self, key: str, kind: str, name: str) -> str | None:
        return self.model.resolve_call(key, kind, name, None)

    # -- hot closure -----------------------------------------------------------
    def _hot_closure(self) -> dict:
        roots: dict[str, str] = {}
        for key, fn in self.model.functions.items():
            for suffix, name, desc in HOT_ROOTS:
                if fn["path"].endswith(suffix) and fn["name"] == name:
                    roots[key] = desc
        out = dict(roots)
        stack = list(roots)
        while stack:
            cur = stack.pop()
            fn = self.model.functions.get(cur)
            if fn is None:
                continue
            for kind, name, recv, _g, _line in fn.get("calls", []):
                tgt = self.model.resolve_call(cur, kind, name, recv)
                if tgt is not None and tgt not in out:
                    out[tgt] = out[cur]
                    stack.append(tgt)
        return out

    # -- provenance tag env ----------------------------------------------------
    def tag_walk(self, key: str, depth: int = _DEPTH):
        """Yield (event, env) in line order for the flaggable events
        (host/truth/combine/dcall), with ``env`` the {ref: tag} map at
        that point. Phase order at one line: flags < unbind < bind."""
        # sort key: (line, phase) — flags 0, unbind 1, src/bindcall 2
        seq = []
        for ev in self.events(key):
            k = ev[0]
            if k in ("host", "combine"):
                seq.append((ev[3], 0, ev))
            elif k == "truth":
                seq.append((ev[2], 0, ev))
            elif k == "dcall":
                seq.append((ev[4], 0, ev))
            elif k == "unbind":
                seq.append((ev[2], 1, ev))
            elif k in ("src", "bindcall"):
                seq.append((ev[-1], 2, ev))
        env: dict[str, str] = {}
        for _line, _ph, ev in sorted(seq, key=lambda t: (t[0], t[1])):
            k = ev[0]
            if k == "unbind":
                env.pop(ev[1], None)
            elif k == "src":
                env[ev[1]] = ev[2]
            elif k == "bindcall":
                tgt = self._resolve(key, ev[2], ev[3])
                tag = (self.ret_tag(tgt, depth - 1)
                       if tgt is not None and depth > 0 else None)
                if tag is not None:
                    env[ev[1]] = tag
                else:
                    env.pop(ev[1], None)
            else:
                yield ev, env

    def ret_tag(self, key: str | None, depth: int = _DEPTH) -> str | None:
        """Provenance tag of a function's return value, or None when
        unknown/ambiguous (ambiguity never produces a finding)."""
        if key is None or depth <= 0:
            return None
        if key in self._ret_tag:
            return self._ret_tag[key]
        self._ret_tag[key] = None  # recursion guard
        tags = set()
        env: dict[str, str] = {}
        seq = []
        for ev in self.events(key):
            k = ev[0]
            if k == "unbind":
                seq.append((ev[2], 1, ev))
            elif k in ("src", "bindcall"):
                seq.append((ev[-1], 2, ev))
            elif k in ("ret", "rettag", "retcall"):
                seq.append((ev[-1], 0, ev))
        for _line, _ph, ev in sorted(seq, key=lambda t: (t[0], t[1])):
            k = ev[0]
            if k == "unbind":
                env.pop(ev[1], None)
            elif k == "src":
                env[ev[1]] = ev[2]
            elif k == "bindcall":
                tgt = self._resolve(key, ev[2], ev[3])
                tag = self.ret_tag(tgt, depth - 1)
                if tag is not None:
                    env[ev[1]] = tag
                else:
                    env.pop(ev[1], None)
            elif k == "rettag":
                tags.add(ev[1])
            elif k == "ret":
                tags.add(env.get(ev[1]))
            elif k == "retcall":
                tgt = self._resolve(key, ev[1], ev[2])
                tags.add(self.ret_tag(tgt, depth - 1))
        out = tags.pop() if len(tags) == 1 else None
        self._ret_tag[key] = out
        return out

    # -- host ops on parameters (rule 20 lookthrough) --------------------------
    def host_param_ops(self, key: str | None) -> dict:
        """{param name: (op, line)} — host-transfer ops a function applies
        DIRECTLY to its own parameters (one lookthrough level)."""
        if key is None:
            return {}
        if key in self._host_param:
            return self._host_param[key]
        params = set(self.params(key))
        out = {}
        for ev in self.events(key):
            if ev[0] == "host" and ev[2] in params and ev[2] not in out:
                out[ev[2]] = (ev[1], ev[3])
        self._host_param[key] = out
        return out

    # -- donation summaries (rule 23) ------------------------------------------
    def _lookup_chain(self, key: str):
        """The function plus its lexical ancestors (closures read the
        enclosing scope's bindings — `_dispatch` calling the parent's
        `train_fn`)."""
        yield key
        fn = self.model.functions.get(key)
        if fn is None:
            return
        path, qual = fn["path"], fn["qual"]
        while "." in qual:
            qual = qual.rsplit(".", 1)[0]
            anc = f"{path}::{qual}"
            if anc in self.model.functions:
                yield anc

    def donating_locals(self, key: str, depth: int = _DEPTH) -> dict:
        """{local name: frozenset(donated positions)} in ONE function:
        literal donating jit binds plus bindings from callees that return
        a donating callable (factories, across modules). Memoized per
        (key, depth) — lookup_donating replays it per dcall."""
        memo = getattr(self, "_donating_memo", None)
        if memo is None:
            memo = self._donating_memo = {}
        mk = (key, depth)
        if mk in memo:
            return memo[mk]
        out: dict[str, frozenset] = {}
        memo[mk] = out
        for ev in self.events(key):
            if ev[0] == "don":
                out[ev[1]] = frozenset(ev[2])
            elif ev[0] == "bindcall" and depth > 0:
                tgt = self._resolve(key, ev[2], ev[3])
                pos = self.returns_donating(tgt, depth - 1)
                if pos:
                    out[ev[1]] = pos
        return out

    def lookup_donating(self, key: str, name: str,
                        depth: int = _DEPTH) -> frozenset | None:
        for k in self._lookup_chain(key):
            got = self.donating_locals(k, depth).get(name)
            if got:
                return got
        return None

    def returns_donating(self, key: str | None,
                         depth: int = _DEPTH) -> frozenset:
        """Donated positions of the callable a function RETURNS (empty
        when it does not return one)."""
        if key is None or depth <= 0:
            return frozenset()
        if key in self._returns_don:
            return self._returns_don[key]
        self._returns_don[key] = frozenset()  # recursion guard
        locals_don = self.donating_locals(key, depth - 1)
        out: frozenset = frozenset()
        for ev in self.events(key):
            if ev[0] == "ret" and ev[1] in locals_don:
                out = out | locals_don[ev[1]]
            elif ev[0] == "retcall":
                tgt = self._resolve(key, ev[1], ev[2])
                out = out | self.returns_donating(tgt, depth - 1)
        self._returns_don[key] = out
        return out

    def ret_pack(self, key: str | None) -> dict:
        """{tuple position: param index} for functions returning a packed
        tuple that carries their own parameters (`_step_args`)."""
        if key is None:
            return {}
        if key in self._ret_pack:
            return self._ret_pack[key]
        params = {p: i for i, p in enumerate(self.params(key))}
        packs: dict[str, list] = {}
        out: dict[int, int] = {}
        for ev in self.events(key):
            if ev[0] == "pack":
                packs[ev[1]] = list(ev[2])
            elif ev[0] == "packext":
                if ev[1] in packs:
                    packs[ev[1]].extend(ev[2])
            elif ev[0] == "retpack":
                for pos, ref in enumerate(ev[1]):
                    if ref in params:
                        out[pos] = params[ref]
            elif ev[0] == "ret" and ev[1] in packs:
                for pos, ref in enumerate(packs[ev[1]]):
                    if ref in params:
                        out[pos] = params[ref]
        self._ret_pack[key] = out
        return out

    def donates_params(self, key: str | None,
                       depth: int = _DEPTH) -> frozenset:
        """Parameter indices a CALL to this function donates (the
        function forwards them into a donated position)."""
        if key is None or depth <= 0:
            return frozenset()
        if key in self._donates_params:
            return self._donates_params[key]
        self._donates_params[key] = frozenset()  # recursion guard
        params = {p: i for i, p in enumerate(self.params(key))}
        out = set()
        for _site, donated in self._donation_sites(key, depth - 1):
            for name in donated:
                if name in params:
                    out.add(params[name])
        self._donates_params[key] = frozenset(out)
        return frozenset(out)

    def _donation_sites(self, key: str, depth: int = _DEPTH) -> list:
        """[( (line, col, endline, endcol), [donated names] )] — every
        dcall in ``key`` that donates arguments, with the names donated.
        Memoized per (key, depth)."""
        memo = getattr(self, "_sites_memo", None)
        if memo is None:
            memo = self._sites_memo = {}
        mk = (key, depth)
        if mk in memo:
            return memo[mk]
        memo[mk] = []
        packs: dict[str, list] = {}
        bindcalls: dict[str, tuple] = {}
        out = []
        for ev in self.events(key):
            if ev[0] == "pack":
                packs[ev[1]] = list(ev[2])
            elif ev[0] == "packext" and ev[1] in packs:
                packs[ev[1]].extend(ev[2])
            elif ev[0] == "bindcall":
                bindcalls[ev[1]] = (ev[2], ev[3], ev[4])
            elif ev[0] == "dcall":
                kind, name, descs = ev[1], ev[2], ev[3]
                ln, col, eln, ecol = ev[4], ev[5], ev[6], ev[7]
                positions = None
                callee_offset = 0
                if kind == "name":
                    positions = self.lookup_donating(key, name, depth)
                if positions is None:
                    tgt = self._resolve(key, kind, name)
                    pp = self.donates_params(tgt, depth)
                    if pp:
                        cparams = self.params(tgt)
                        callee_offset = (1 if cparams
                                         and cparams[0] == "self"
                                         and kind in ("self", "attr")
                                         else 0)
                        positions = frozenset(p - callee_offset
                                              for p in pp
                                              if p >= callee_offset)
                if not positions:
                    continue
                donated = []
                star = next((d for d in descs if d[0] == "star"), None)
                if star is not None and star[1]:
                    elts = packs.get(star[1])
                    if elts is None and star[1] in bindcalls:
                        bkind, bname, bargs = bindcalls[star[1]]
                        btgt = self._resolve(key, bkind, bname)
                        rp = self.ret_pack(btgt)
                        elts = {}
                        for pos, pidx in rp.items():
                            if pidx < len(bargs) and bargs[pidx]:
                                elts[pos] = bargs[pidx]
                        elts = [elts.get(i) for i in
                                range(max(elts, default=-1) + 1)]
                    if elts:
                        for p in positions:
                            if p < len(elts) and elts[p]:
                                donated.append(elts[p])
                else:
                    for p in positions:
                        if p < len(descs) and descs[p][0] == "name" \
                                and descs[p][1]:
                            donated.append(descs[p][1])
                if donated:
                    out.append(((ln, col, eln, ecol), donated))
        memo[mk] = out
        return out


# ---------------------------------------------------------------------------
# rule 20 — host-transfer-in-hot-path
# ---------------------------------------------------------------------------
class HostTransferInHotPath(ProjectRule):
    id = "host-transfer-in-hot-path"
    doc = ("np.*/float()/.item()/implicit-bool on a device-provenance "
           "value inside a hot section (train chunk loop, MRTask "
           "dispatch, serving score path, Cleaner sweep) — each one is a "
           "blocking device->host sync per iteration; use an explicit "
           "jax.device_get at a declared sync point")

    def check(self, model: ProjectModel) -> list:
        info = ProvInfo.of(model)
        out = []
        for key in sorted(info.hot):
            fn = model.functions.get(key)
            if fn is None or not in_scope(fn["path"]):
                continue
            root = info.hot[key]
            for ev, env in info.tag_walk(key):
                if ev[0] == "host" and env.get(ev[2]) in _DEVICE_TAGS:
                    out.append((fn["path"], ev[3],
                                f"{ev[1]} on device-provenance value "
                                f"'{ev[2]}' inside the {root} hot path — "
                                f"an implicit device->host sync per "
                                f"iteration; fetch once via an explicit "
                                f"jax.device_get at a declared sync "
                                f"point (H2O_TPU_SANITIZE=transfers is "
                                f"the runtime twin)",
                                ev[4], ev[5]))
                elif ev[0] == "truth" and env.get(ev[1]) in _DEVICE_TAGS:
                    out.append((fn["path"], ev[2],
                                f"implicit bool() of device-provenance "
                                f"value '{ev[1]}' inside the {root} hot "
                                f"path — a hidden device->host sync; "
                                f"read it once explicitly",
                                ev[3], ev[4]))
                elif ev[0] == "dcall":
                    tgt = info._resolve(key, ev[1], ev[2])
                    hp = info.host_param_ops(tgt)
                    if not hp:
                        continue
                    cparams = info.params(tgt)
                    off = (1 if cparams and cparams[0] == "self"
                           and ev[1] in ("self", "attr") else 0)
                    for i, d in enumerate(ev[3]):
                        if d[0] != "name" or env.get(d[1]) \
                                not in _DEVICE_TAGS:
                            continue
                        pidx = i + off
                        if pidx < len(cparams) \
                                and cparams[pidx] in hp:
                            op, _l = hp[cparams[pidx]]
                            out.append((
                                fn["path"], ev[4],
                                f"device-provenance value '{d[1]}' "
                                f"passed to {ev[2]}(), which applies "
                                f"{op} to it — an implicit device->"
                                f"host sync hidden one call below the "
                                f"{root} hot path",
                                ev[5], ev[7]))
        return out


# ---------------------------------------------------------------------------
# rule 21 — mixed-sharding-combine
# ---------------------------------------------------------------------------
class MixedShardingCombine(ProjectRule):
    id = "mixed-sharding-combine"
    doc = ("row-sharded and replicated provenance meeting in one host-"
           "level op outside shard_map — GSPMD silently inserts a "
           "resharding collective; re-place one operand via mesh.put_* "
           "or move the op into shard_map")

    def check(self, model: ProjectModel) -> list:
        info = ProvInfo.of(model)
        out = []
        for key in sorted(model.functions):
            fn = model.functions[key]
            if not in_scope(fn["path"]):
                continue
            for ev, env in info.tag_walk(key):
                if ev[0] != "combine":
                    continue
                tags = {env.get(ev[1]), env.get(ev[2])}
                if tags == {"row", "rep"}:
                    out.append((fn["path"], ev[3],
                                f"row-sharded '{ev[1] if env.get(ev[1]) == 'row' else ev[2]}' "
                                f"combined with replicated "
                                f"'{ev[2] if env.get(ev[2]) == 'rep' else ev[1]}' "
                                f"outside shard_map — GSPMD will "
                                f"silently reshard one side per call; "
                                f"re-place one operand (mesh.put_*) or "
                                f"move the op into shard_map",
                                ev[4], ev[5]))
        return out


# ---------------------------------------------------------------------------
# rule 22 — recompile-hazard
# ---------------------------------------------------------------------------
class RecompileHazard(ProjectRule):
    id = "recompile-hazard"
    doc = ("jit cache key that cannot stabilize: jit/tracked/.lower "
           "construction inside a loop, a per-iteration Python value or "
           "non-hashable literal in a static_argnums position, or a "
           "per-iteration comprehension argument — every call compiles; "
           "H2O_TPU_SANITIZE=recompiles raises on the compile this "
           "predicts")

    def check(self, model: ProjectModel) -> list:
        out = []
        for key in sorted(model.functions):
            fn = model.functions[key]
            if not in_scope(fn["path"]):
                continue
            jit_static: dict[str, list] = {}
            for ev in (fn.get("prov") or []):
                if ev[0] == "jit":
                    jit_static[ev[1]] = list(ev[2])
                elif ev[0] == "don":
                    jit_static.setdefault(ev[1], [])
                elif ev[0] == "jitloop":
                    what = ("jax.jit/programs.tracked" if ev[1] == "jit"
                            else ".lower(...)")
                    out.append((fn["path"], ev[2],
                                f"{what} constructed inside a loop — a "
                                f"fresh callable per iteration gets a "
                                f"fresh compile cache entry every time; "
                                f"hoist the construction out of the "
                                f"loop", ev[3], ev[4]))
                elif ev[0] == "dcall" and ev[1] == "name" \
                        and ev[2] in jit_static:
                    descs = ev[3]
                    ln, col, ecol = ev[4], ev[5], ev[7]
                    for p in jit_static[ev[2]]:
                        if p >= len(descs):
                            continue
                        d = descs[p]
                        if d[0] == "name" and d[2]:
                            out.append((
                                fn["path"], ln,
                                f"per-iteration value '{d[1]}' in "
                                f"static_argnums position {p} of "
                                f"jitted '{ev[2]}' — a new cache key "
                                f"(and a recompile) every call; make "
                                f"it a traced argument or hoist it",
                                col, ecol))
                        elif d[0] in ("list", "dict", "set"):
                            out.append((
                                fn["path"], ln,
                                f"non-hashable {d[0]} literal in "
                                f"static_argnums position {p} of "
                                f"jitted '{ev[2]}' — jit static "
                                f"arguments must be hashable (this "
                                f"raises, or worse: a tuple-ified "
                                f"copy keys the cache per identity)",
                                col, ecol))
                    for i, d in enumerate(descs):
                        if d[0] == "comp" and d[2]:
                            out.append((
                                fn["path"], ln,
                                f"per-iteration comprehension as "
                                f"argument {i} of jitted '{ev[2]}' — "
                                f"pytree length churn gives a new "
                                f"cache key whenever the length "
                                f"moves; pad to a fixed shape or "
                                f"hoist", col, ecol))
        return out


# ---------------------------------------------------------------------------
# rule 23 — donate-across-calls
# ---------------------------------------------------------------------------
class DonateAcrossCalls(ProjectRule):
    id = "donate-across-calls"
    doc = ("variable read after riding a donated position across a call "
           "boundary — donating factories resolve through the call "
           "graph, donation propagates through tuple packs and f(*args) "
           "star-dispatch, and param-forwarding helpers summarize as "
           "donating; rule 18's file-local analysis made "
           "interprocedural")

    def check(self, model: ProjectModel) -> list:
        info = ProvInfo.of(model)
        out = []
        for key in sorted(model.functions):
            fn = model.functions[key]
            if not in_scope(fn["path"]):
                continue
            sites = info._donation_sites(key)
            if not sites:
                continue
            seq = []
            for (ln, col, eln, ecol), donated in sites:
                for name in donated:
                    seq.append((eln, 1, ("don", name, ln)))
            for ev in info.events(key):
                if ev[0] == "use":
                    seq.append((ev[2], 0, ("use", ev[1], ev[2], ev[3],
                                           ev[4])))
                elif ev[0] == "kill":
                    seq.append((ev[2], 2, ("kill", ev[1])))
            donated_now: dict[str, int] = {}
            for _line, _ph, item in sorted(seq, key=lambda t: (t[0],
                                                               t[1])):
                if item[0] == "use" and item[1] in donated_now:
                    out.append((fn["path"], item[2],
                                f"read of '{item[1]}' after it rode a "
                                f"donated position across a call "
                                f"boundary (donated at line "
                                f"{donated_now[item[1]]}) — the buffer "
                                f"is deleted at dispatch; rebind the "
                                f"result or copy before dispatching",
                                item[3], item[4]))
                    del donated_now[item[1]]
                elif item[0] == "don":
                    donated_now[item[1]] = item[2]
                elif item[0] == "kill":
                    donated_now.pop(item[1], None)
        return out


DATAFLOW_RULES = (HostTransferInHotPath, MixedShardingCombine,
                  RecompileHazard, DonateAcrossCalls)
