#!/usr/bin/env python3
"""Perf-regression gate — a fresh bench sidecar run vs a pinned baseline.

Until this tool, the bench trajectory was a human ritual: run bench.py,
eyeball the JSONL against the last re-anchor, hope nobody ships a silent
2x slowdown. This gate makes the comparison exit-coded: per-leg tolerance
bands on walls, peak HBM bytes, compile hygiene and parity flags, with a
human-readable delta table and a nonzero exit naming the first offending
(leg, metric) pair.

Usage::

    python tools/bench_gate.py --run BENCH_partial.jsonl \
        [--baseline BENCH_r06_baseline.jsonl] [--bands wall=0.4,peak=0.5]

Semantics:

- Only legs present in BOTH files are compared; extra/missing legs are
  reported, never failed (a smoke run gates the legs it ran).
- Walls/throughput compare ONLY when the two runs' configs match (the
  ``bench_run`` header rows/trees, and per-record ``rows`` where the leg
  carries one) — cross-scale wall deltas are noise, not regressions.
  Parity flags and compile hygiene compare unconditionally.
- Bands are fractional slack: ``wall=0.25`` fails a wall more than 25%
  over baseline. Leg-scoped overrides (``gbm.wall=0.6``) win over metric
  ones; ``--bands`` wins over ``H2O_TPU_BENCH_GATE_BANDS`` (registered in
  knobs.py; read directly here so the gate needs no h2o_tpu import).
- Sidecar files may contain several appended runs — the LAST complete
  run (from the final ``bench_run`` header) is compared.

Exit codes: 0 = within bands, 1 = regression (named), 2 = usage/parse.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: default fractional bands. wall: +25% (the seeded regression fixture is
#: a 30% slowdown — it must fail); peak bytes: +25%; AUC: absolute drop
#: 0.02; throughput (rows/s, req/s): -25%.
DEFAULT_BANDS = {"wall": 0.25, "peak": 0.25, "auc": 0.02, "thru": 0.25}

#: per-leg comparable metrics: (record key, band kind, direction).
#: keys may be dotted paths into nested record blocks
#: ("concurrent.pooled_req_s"). direction: "up" = bigger is worse
#: (walls, bytes), "down" = smaller is worse (AUC, throughput)
LEG_METRICS = {
    "gbm": [("score_once_s", "wall", "up"),
            ("cadence10_s", "wall", "up"),
            ("train_auc", "auc", "down")],
    "glm_irlsm": [("wall_s", "wall", "up")],
    "glm_cod": [("wall_s", "wall", "up")],
    "gam_irlsm": [("wall_s", "wall", "up")],
    "rulefit": [("wall_s", "wall", "up")],
    "sort": [("wall_s", "wall", "up")],
    "merge": [("wall_s", "wall", "up")],
    "airlines116m": [("wall_s", "wall", "up"),
                     ("train_auc", "auc", "down"),
                     ("pipeline_speedup_x", "thru", "down")],
    "serving": [("rows_per_s", "thru", "down")],
    "serving_wire": [("concurrent.pooled_req_s", "thru", "down"),
                     ("sequential.pooled_req_s", "thru", "down")],
    "recovery": [("train_wall_s", "wall", "up")],
    "binned_store": [("reduction_x", "thru", "down")],
    "workload": [("total_wall_s", "wall", "up"),
                 ("score_p99_ms_max", "wall", "up")],
}

#: flags that must hold whenever both records carry them (scale-free)
LEG_FLAGS = {
    "airlines116m": [("forest_parity", True),
                     ("uncached_compiles_warm", 0)],
    "sharded": [("forest_struct_equal", True), ("per_shard_bytes_ok", True)],
    "recovery": [("resume_bit_parity", True)],
    "serving": [("recompiles", 0)],
    "serving_wire": [("recompiles", 0)],
    "workload": [("all_completed", True), ("preemption_observed", True)],
}


def _get(rec: dict, key: str):
    """Record lookup with dotted-path support into nested blocks."""
    cur = rec
    for part in key.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def parse_bands(spec: str) -> dict:
    out = {}
    for tok in filter(None, (t.strip() for t in (spec or "").split(","))):
        k, _, v = tok.partition("=")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            raise SystemExit(f"bench_gate: bad band spec {tok!r} "
                             f"(expected metric=frac)")
    return out


def band_for(bands: dict, leg: str, kind: str) -> float:
    return bands.get(f"{leg}.{kind}", bands.get(kind, DEFAULT_BANDS[kind]))


def load_last_run(path: str) -> tuple[dict, dict]:
    """(header, {workload: record}) of the LAST run in a sidecar file."""
    header: dict = {}
    legs: dict = {}
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    d = json.loads(ln)
                except json.JSONDecodeError:
                    continue  # torn tail of a crashed run
                if "bench_run" in d:
                    header, legs = d["bench_run"], {}
                elif "workload" in d:
                    legs[d["workload"]] = d.get("record", {})
    except OSError as e:
        raise SystemExit(f"bench_gate: cannot read {path}: {e}")
    return header, legs


def telemetry_peak(rec: dict):
    t = rec.get("telemetry") or {}
    g = t.get("cleaner.hbm.live.bytes") or {}
    return g.get("peak")


def comparable_scale(bhdr, rhdr, bleg, rleg) -> bool:
    for k in ("rows", "ntrees"):
        if k in bleg and k in rleg and bleg[k] != rleg[k]:
            return False
    for k in ("rows", "ntrees", "sort_rows"):
        if bhdr.get(k) is not None and rhdr.get(k) is not None \
                and bhdr[k] != rhdr[k]:
            return False
    return True


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(prog="python tools/bench_gate.py")
    ap.add_argument("--run", required=True,
                    help="fresh bench sidecar JSONL to gate")
    ap.add_argument("--baseline",
                    default=os.path.join(root, "BENCH_r06_baseline.jsonl"))
    ap.add_argument("--bands", default=None,
                    help="metric=frac[,leg.metric=frac] overrides "
                         "(default: H2O_TPU_BENCH_GATE_BANDS, then "
                         f"{DEFAULT_BANDS})")
    args = ap.parse_args(argv)

    # registered in knobs.py (H2O_TPU_BENCH_GATE_BANDS); read via
    # os.environ so this tool stays import-free of the jax stack
    bands = parse_bands(args.bands if args.bands is not None
                        else os.environ.get("H2O_TPU_BENCH_GATE_BANDS", ""))

    bhdr, base = load_last_run(args.baseline)
    rhdr, run = load_last_run(args.run)
    if not base:
        print(f"bench_gate: no records in baseline {args.baseline}")
        return 2
    if not run:
        print(f"bench_gate: no records in run {args.run}")
        return 2

    rows = []
    failures = []

    def check(leg, metric, bval, rval, band, worse_dir, scaled=True):
        if bval is None or rval is None:
            rows.append((leg, metric, bval, rval, "-", "n/a"))
            return
        if not scaled:
            rows.append((leg, metric, bval, rval, "-", "skip (scale)"))
            return
        if isinstance(bval, bool) or isinstance(rval, bool):
            ok = bval == rval
            rows.append((leg, metric, bval, rval, "==",
                         "ok" if ok else "FAIL"))
            if not ok:
                failures.append((leg, metric, bval, rval))
            return
        try:
            delta = (rval - bval) / bval if bval else 0.0
        except TypeError:
            rows.append((leg, metric, bval, rval, "-", "n/a"))
            return
        if worse_dir == "up":
            ok = delta <= band
        else:
            ok = -delta <= band
        rows.append((leg, metric, bval, rval, f"{delta:+.1%}",
                     "ok" if ok else "FAIL"))
        if not ok:
            failures.append((leg, metric, bval, rval))

    for leg in sorted(set(base) & set(run)):
        bleg, rleg = base[leg], run[leg]
        scaled = comparable_scale(bhdr, rhdr, bleg, rleg)
        for key, kind, direction in LEG_METRICS.get(leg, []):
            bval, rval = _get(bleg, key), _get(rleg, key)
            if bval is None and rval is None:
                continue
            if kind == "auc":
                # absolute drop band, not relative
                if bval is not None and rval is not None and scaled:
                    band = band_for(bands, leg, "auc")
                    ok = (bval - rval) <= band
                    rows.append((leg, key, bval, rval,
                                 f"{rval - bval:+.4f}",
                                 "ok" if ok else "FAIL"))
                    if not ok:
                        failures.append((leg, key, bval, rval))
                else:
                    rows.append((leg, key, bval, rval, "-",
                                 "n/a" if None in (bval, rval)
                                 else "skip (scale)"))
                continue
            check(leg, key, bval, rval, band_for(bands, leg, kind),
                  direction, scaled=scaled)
        for key, want in LEG_FLAGS.get(leg, []):
            # display the baseline's RECORDED value (older baselines may
            # predate a flag — then the required value stands in); the
            # verdict always compares the run against the requirement
            bval, rval = _get(bleg, key), _get(rleg, key)
            if bval is None:
                bval = want
            if rval is None:
                continue
            ok = rval == want
            rows.append((leg, key, bval, rval, "==", "ok" if ok else "FAIL"))
            if not ok:
                failures.append((leg, key, want, rval))
        bpk, rpk = telemetry_peak(bleg), telemetry_peak(rleg)
        if bpk and rpk:
            check(leg, "hbm_peak_bytes", bpk, rpk,
                  band_for(bands, leg, "peak"), "up", scaled=scaled)

    missing = sorted(set(base) - set(run))
    extra = sorted(set(run) - set(base))

    wl = max([len(r[0]) for r in rows] + [8])
    ml = max([len(str(r[1])) for r in rows] + [6])
    print(f"bench_gate: run={args.run} vs baseline={args.baseline}")
    print(f"{'leg'.ljust(wl)}  {'metric'.ljust(ml)}  "
          f"{'baseline':>14}  {'run':>14}  {'delta':>8}  verdict")
    for leg, metric, bval, rval, delta, verdict in rows:
        def fmt(v):
            if isinstance(v, float):
                return f"{v:.4g}"
            return str(v)
        print(f"{leg.ljust(wl)}  {str(metric).ljust(ml)}  "
              f"{fmt(bval):>14}  {fmt(rval):>14}  {delta:>8}  {verdict}")
    if missing:
        print(f"legs in baseline only (not gated): {', '.join(missing)}")
    if extra:
        print(f"legs in run only (not gated): {', '.join(extra)}")
    gated = [r for r in rows if r[5] in ("ok", "FAIL")]
    if not gated:
        # a run that shares no gateable metric with the baseline (typo'd
        # workload list, renamed legs) must NOT read as a green gate
        print("\nbench_gate: FAIL — no metric was actually compared "
              "(no overlapping legs, or every comparison skipped); "
              "check the run's workload list against the baseline")
        return 1
    if failures:
        print(f"\nbench_gate: FAIL — {len(failures)} regression(s):")
        for leg, metric, bval, rval in failures:
            print(f"  {leg}.{metric}: baseline {bval!r} -> run {rval!r}")
        return 1
    print("\nbench_gate: ok — all compared legs within bands")
    return 0


if __name__ == "__main__":
    sys.exit(main())
