"""Phase profiler for the RuleFit benchmark workload (VERDICT r4 weak #1).

Times tree generation / rule extraction / streaming L1 GLM (with step-call
count) / support pass / scoring separately at bench shape, warm and cold.
Run on the real chip:  python tools/profile_rulefit.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

NROW = int(os.environ.get("H2O_TPU_BENCH_ROWS", 11_000_000))

import bench  # noqa: E402

bench._enable_compile_cache()

from h2o_tpu.models import rulefit as rf  # noqa: E402

PHASES = {}


def timed(name, fn):
    def wrap(*a, **k):
        t0 = time.time()
        out = fn(*a, **k)
        PHASES[name] = PHASES.get(name, 0.0) + (time.time() - t0)
        PHASES.setdefault(name + "_n", 0)
        PHASES[name + "_n"] += 1
        return out
    return wrap


# patch tree builders
_orig_drf_build = rf.DRF.build_impl
rf.DRF.build_impl = timed("trees", _orig_drf_build)
_orig_gbm_build = rf.GBM.build_impl
rf.GBM.build_impl = timed("trees", _orig_gbm_build)
rf.extract_rules = timed("extract", rf.extract_rules)
rf.RuleFit._fit_streaming = timed("l1_glm", rf.RuleFit._fit_streaming)
rf._stream_rule_support = timed("support", rf._stream_rule_support)

_orig_step = rf._stream_step


def patched_stream_step(family, rb):
    raw = _orig_step(family, rb)

    def step(*a, **k):
        import jax
        t0 = time.time()
        out = raw(*a, **k)
        jax.block_until_ready(out)
        PHASES["step"] = PHASES.get("step", 0.0) + (time.time() - t0)
        PHASES["step_n"] = PHASES.get("step_n", 0) + 1
        return out
    return step


rf._stream_step = patched_stream_step

_orig_score0 = rf.RuleFitModel.score0
rf.RuleFitModel.score0 = timed("score0", _orig_score0)


def run():
    global PHASES
    p = rf.RuleFitParameters(training_frame=fr, response_column="response",
                             model_type="rules_and_linear",
                             min_rule_length=3, max_rule_length=3, seed=42)
    PHASES = {}
    t0 = time.time()
    m = rf.RuleFit(p).train_model()
    total = time.time() - t0
    acct = sum(v for k, v in PHASES.items() if not k.endswith("_n")
               and k != "step")
    print({"total_s": round(total, 2),
           "unaccounted_s": round(total - acct, 2),
           **{k: (round(v, 2) if isinstance(v, float) else v)
              for k, v in sorted(PHASES.items())}}, flush=True)
    n_rules = len(m.rules)
    print({"n_rules": n_rules, "P1": n_rules + len(m.lin_names) + 1},
          flush=True)


print(f"building frame nrow={NROW}", flush=True)
fr = bench._higgs_frame(NROW)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.block_until_ready([jnp.sum(v.data) for v in fr.vecs
                       if v.data is not None])
print("cold run:", flush=True)
run()
print("warm run:", flush=True)
run()
