#!/usr/bin/env bash
# CI gate — graftlint (24 rules, baseline-gated) + the tier-1 pytest line,
# as ONE exit-coded command. Either failing fails the gate; both always
# run so a single CI pass reports lint findings AND test failures.
#
# Usage:
#   tools/ci_gate.sh                 # text findings
#   tools/ci_gate.sh --bench-smoke   # + the 50k-row pipelined GBM bench leg
#   tools/ci_gate.sh --bench-gate    # + smoke bench at baseline config,
#                                    #   gated vs BENCH_r06_baseline.jsonl
#   tools/ci_gate.sh --sanitize-stress  # + serving+train+sweep stress with
#                                    #   ALL FOUR sanitizer arms armed
#   tools/ci_gate.sh --health-gate   # + boot a server, assert /3/Health
#                                    #   ready -> wedged (typed reason) ->
#                                    #   recovered across a failpoint drill
#   GRAFTLINT_FORMAT=github tools/ci_gate.sh   # ::error annotations
#   GRAFTLINT_JOBS=4 tools/ci_gate.sh          # parallel lint scan
#
# --bench-smoke runs the airlines bench leg (the pipelined-training
# scoreboard) at 50k rows with H2O_TPU_PIPELINE on and asserts rc=0,
# forest_parity=true (pipelined forest + predictions bit-equal to the
# synchronous oracle) and 0 steady-state uncached compiles on the warm
# train. The >=1.25x speedup stays a recorded number, not a gate — CI
# machines' walls are noisy; parity and compile hygiene are not.
#
# --bench-gate runs the gbm+glm legs at the BENCH_r06 baseline's exact
# config (60k rows / 100 trees, so walls are comparable) and pipes the
# sidecar through tools/bench_gate.py: per-leg tolerance bands on wall,
# peak HBM bytes, AUC, parity flags — nonzero exit names the regressed
# (leg, metric). Band overrides: H2O_TPU_BENCH_GATE_BANDS.
#
# --health-gate boots a REAL server (watchdog armed at a 100ms sweep),
# asserts GET /3/Health reports ready over the wire, arms the registered
# watchdog.trip failpoint to force-wedge every detector, asserts the
# endpoint degrades with the TYPED watchdog-trip reason, disarms, and
# asserts recovery once the trips age out — the full signal path the
# autoscaling loop will poll, exit-coded.
#
# --sanitize-stress re-runs the PR 11 serving+train+sweep stress pass
# with H2O_TPU_SANITIZE=locks,guards,transfers,recompiles all armed
# (instrumented locks + guard assertions + transfer guards over every
# hot section + steady-state compile scopes) and asserts SILENCE —
# zero typed violations across concurrent scoring, a real GBM train,
# and forced Cleaner sweeps. The drill twins (failpoint + live
# host->device trip + serving bucket-miss) ride along so the typed
# violation -> flight-bundle seams stay exercised. These tests also run
# inside the tier-1 line above; the flag is the DELIBERATE duplicate — a
# named, exit-coded leg a nightly/hardware pipeline can point at without
# parsing the 1100-test tier-1 summary, re-run in a fresh interpreter so
# sanitizer arming never inherits tier-1 process state.
set -u -o pipefail
cd "$(dirname "$0")/.."

fmt="${GRAFTLINT_FORMAT:-text}"
jobs="${GRAFTLINT_JOBS:-2}"
bench_smoke=0
bench_gate=0
sanitize_stress=0
health_gate=0
for arg in "$@"; do
    case "$arg" in
        --bench-smoke) bench_smoke=1 ;;
        --bench-gate) bench_gate=1 ;;
        --sanitize-stress) sanitize_stress=1 ;;
        --health-gate) health_gate=1 ;;
        *) echo "ci_gate.sh: unknown argument '$arg'" >&2; exit 2 ;;
    esac
done

echo "== graftlint =="
python -m tools.graftlint --format "$fmt" --jobs "$jobs"
lint_rc=$?

echo "== tier-1 pytest =="
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
test_rc=$?

bench_rc=0
if [ "$bench_smoke" -eq 1 ]; then
    echo "== bench smoke (pipelined 50k-row GBM) =="
    sidecar="$(mktemp /tmp/h2o_tpu_bench_smoke.XXXXXX.jsonl)"
    timeout -k 10 900 env JAX_PLATFORMS=cpu \
        H2O_TPU_BENCH_WORKLOADS=airlines \
        H2O_TPU_BENCH_AIRLINES_ROWS=50000 \
        H2O_TPU_PIPELINE=1 \
        H2O_TPU_BENCH_SIDECAR="$sidecar" \
        python bench.py > /dev/null
    bench_rc=$?
    if [ "$bench_rc" -eq 0 ]; then
        python - "$sidecar" <<'EOF'
import json, sys

rec = None
for line in open(sys.argv[1]):
    d = json.loads(line)
    if d.get("workload") == "airlines116m":
        rec = d["record"]
assert rec is not None, "airlines leg record missing from sidecar"
assert rec["forest_parity"] is True, \
    f"pipelined forest NOT bit-equal to the synchronous oracle: {rec}"
assert rec["uncached_compiles_warm"] == 0, \
    f"steady-state uncached compiles: {rec['uncached_compiles_warm']}"
print(json.dumps({"bench_smoke": "ok",
                  "wall_s": rec["wall_s"],
                  "wall_sync_s": rec["wall_sync_s"],
                  "pipeline_speedup_x": rec["pipeline_speedup_x"],
                  "overlap_ratio": rec["overlap_ratio"]}))
EOF
        bench_rc=$?
    fi
    rm -f "$sidecar"
fi

gate_rc=0
if [ "$bench_gate" -eq 1 ]; then
    echo "== bench gate (gbm+glm @ BENCH_r06 config vs baseline bands) =="
    sidecar="$(mktemp /tmp/h2o_tpu_bench_gate.XXXXXX.jsonl)"
    timeout -k 10 1500 env JAX_PLATFORMS=cpu \
        H2O_TPU_BENCH_WORKLOADS=gbm,glm \
        H2O_TPU_BENCH_ROWS=60000 \
        H2O_TPU_BENCH_TREES=100 \
        H2O_TPU_BENCH_SIDECAR="$sidecar" \
        python bench.py > /dev/null
    gate_rc=$?
    if [ "$gate_rc" -eq 0 ]; then
        python tools/bench_gate.py --run "$sidecar"
        gate_rc=$?
    fi
    rm -f "$sidecar"
fi

stress_rc=0
if [ "$sanitize_stress" -eq 1 ]; then
    echo "== sanitize stress (serving+train+sweep, all four arms armed) =="
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        "tests/test_sanitizer.py::TestStressSilence::test_serving_train_sweep_stress_stays_silent[locks,guards,transfers,recompiles]" \
        "tests/test_sanitizer.py::TestTransferSanitizer::test_live_h2d_guard_trips_typed_on_cpu_mesh" \
        "tests/test_sanitizer.py::TestTransferSanitizer::test_failpoint_drill_types_and_bundles" \
        "tests/test_sanitizer.py::TestRecompileSanitizer::test_serving_bucket_miss_raises_typed_and_bundles"
    stress_rc=$?
fi

health_rc=0
if [ "$health_gate" -eq 1 ]; then
    echo "== health gate (/3/Health ready -> wedged -> recovered) =="
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        H2O_TPU_WATCHDOG_MS=100 \
        python - <<'EOF'
import json
import time
import urllib.request

from h2o_tpu.api.server import H2OServer
from h2o_tpu.utils import failpoints

srv = H2OServer(port=54941).start()


def health():
    with urllib.request.urlopen(f"{srv.url}/3/Health", timeout=10) as r:
        return json.loads(r.read().decode())


h = health()
assert h["live"] and h["ready"], \
    f"expected ready on boot, degraded: {h['degraded']}"

# wedge: the registered watchdog.trip failpoint force-trips all four
# detectors on the next sweep — nothing is actually wrong, which is the
# point: the gate drills the SIGNAL path, not a real outage
failpoints.arm("watchdog.trip", "raise*4")
deadline = time.time() + 20
while time.time() < deadline:
    h = health()
    if not h["ready"]:
        break
    time.sleep(0.1)
assert not h["ready"], "health never degraded under the armed drill"
reasons = {d["reason"] for d in h["degraded"]}
assert "watchdog-trip" in reasons, f"wrong typed reasons: {reasons}"

# recover: disarm, trips age out after 10 sweep intervals (~1s here)
failpoints.disarm("watchdog.trip")
deadline = time.time() + 30
while time.time() < deadline:
    h = health()
    if h["ready"]:
        break
    time.sleep(0.2)
assert h["ready"], f"health never recovered after disarm: {h['degraded']}"
srv.stop()
print(json.dumps({"health_gate": "ok"}))
EOF
    health_rc=$?
fi

echo "== gate: lint rc=${lint_rc}, tests rc=${test_rc}, bench rc=${bench_rc}, bench-gate rc=${gate_rc}, sanitize-stress rc=${stress_rc}, health rc=${health_rc} =="
if [ "$lint_rc" -ne 0 ] || [ "$test_rc" -ne 0 ] || [ "$bench_rc" -ne 0 ] || [ "$gate_rc" -ne 0 ] || [ "$stress_rc" -ne 0 ] || [ "$health_rc" -ne 0 ]; then
    exit 1
fi
exit 0
