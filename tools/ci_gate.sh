#!/usr/bin/env bash
# CI gate — graftlint (17 rules, baseline-gated) + the tier-1 pytest line,
# as ONE exit-coded command. Either failing fails the gate; both always
# run so a single CI pass reports lint findings AND test failures.
#
# Usage:
#   tools/ci_gate.sh                 # text findings
#   GRAFTLINT_FORMAT=github tools/ci_gate.sh   # ::error annotations
#   GRAFTLINT_JOBS=4 tools/ci_gate.sh          # parallel lint scan
set -u -o pipefail
cd "$(dirname "$0")/.."

fmt="${GRAFTLINT_FORMAT:-text}"
jobs="${GRAFTLINT_JOBS:-2}"

echo "== graftlint =="
python -m tools.graftlint --format "$fmt" --jobs "$jobs"
lint_rc=$?

echo "== tier-1 pytest =="
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
test_rc=$?

echo "== gate: lint rc=${lint_rc}, tests rc=${test_rc} =="
if [ "$lint_rc" -ne 0 ] || [ "$test_rc" -ne 0 ]; then
    exit 1
fi
exit 0
