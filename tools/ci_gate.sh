#!/usr/bin/env bash
# CI gate — graftlint (24 rules, baseline-gated) + the tier-1 pytest line,
# as ONE exit-coded command. Either failing fails the gate; both always
# run so a single CI pass reports lint findings AND test failures.
#
# Usage:
#   tools/ci_gate.sh                 # text findings
#   tools/ci_gate.sh --bench-smoke   # + the 50k-row pipelined GBM bench leg
#   tools/ci_gate.sh --bench-gate    # + smoke bench at baseline config,
#                                    #   gated vs BENCH_r06_baseline.jsonl
#   tools/ci_gate.sh --sanitize-stress  # + serving+train+sweep stress with
#                                    #   ALL FOUR sanitizer arms armed
#   tools/ci_gate.sh --health-gate   # + boot a server, assert /3/Health
#                                    #   ready -> wedged (typed reason) ->
#                                    #   recovered across a failpoint drill
#   tools/ci_gate.sh --workload-gate # + boot a server with 2 managed
#                                    #   slots, 3-tenant mixed stress with
#                                    #   boundary kills auto-resumed, SLO
#                                    #   held, zero sanitizer violations
#   GRAFTLINT_FORMAT=github tools/ci_gate.sh   # ::error annotations
#   GRAFTLINT_JOBS=4 tools/ci_gate.sh          # parallel lint scan
#
# --bench-smoke runs the airlines bench leg (the pipelined-training
# scoreboard) at 50k rows with H2O_TPU_PIPELINE on and asserts rc=0,
# forest_parity=true (pipelined forest + predictions bit-equal to the
# synchronous oracle) and 0 steady-state uncached compiles on the warm
# train. The >=1.25x speedup stays a recorded number, not a gate — CI
# machines' walls are noisy; parity and compile hygiene are not.
#
# --bench-gate runs the gbm+glm legs at the BENCH_r06 baseline's exact
# config (60k rows / 100 trees, so walls are comparable) and pipes the
# sidecar through tools/bench_gate.py: per-leg tolerance bands on wall,
# peak HBM bytes, AUC, parity flags — nonzero exit names the regressed
# (leg, metric). Band overrides: H2O_TPU_BENCH_GATE_BANDS.
#
# --health-gate boots a REAL server (watchdog armed at a 100ms sweep),
# asserts GET /3/Health reports ready over the wire, arms the registered
# watchdog.trip failpoint to force-wedge every detector, asserts the
# endpoint degrades with the TYPED watchdog-trip reason, disarms, and
# asserts recovery once the trips age out — the full signal path the
# autoscaling loop will poll, exit-coded.
#
# --workload-gate boots a REAL server with H2O_TPU_WORKLOAD_SLOTS=2 and
# the recompile sanitizer armed, then (1) kills a REST-submitted GBM at
# EVERY chunk boundary via the workload.preempt failpoint and asserts the
# scheduler entry auto-resumes to DONE each time, (2) runs a 3-tenant
# mixed-priority stress (three concurrent REST builds + a serving score
# loop) and asserts every tenant's job completes (no starvation), GET
# /3/Health stays ready (per-tenant serving SLO held) and the sanitizer
# + steady-state recompile counters read ZERO.
#
# --sanitize-stress re-runs the PR 11 serving+train+sweep stress pass
# with H2O_TPU_SANITIZE=locks,guards,transfers,recompiles all armed
# (instrumented locks + guard assertions + transfer guards over every
# hot section + steady-state compile scopes) and asserts SILENCE —
# zero typed violations across concurrent scoring, a real GBM train,
# and forced Cleaner sweeps. The drill twins (failpoint + live
# host->device trip + serving bucket-miss) ride along so the typed
# violation -> flight-bundle seams stay exercised. These tests also run
# inside the tier-1 line above; the flag is the DELIBERATE duplicate — a
# named, exit-coded leg a nightly/hardware pipeline can point at without
# parsing the 1100-test tier-1 summary, re-run in a fresh interpreter so
# sanitizer arming never inherits tier-1 process state.
set -u -o pipefail
cd "$(dirname "$0")/.."

fmt="${GRAFTLINT_FORMAT:-text}"
jobs="${GRAFTLINT_JOBS:-2}"
bench_smoke=0
bench_gate=0
sanitize_stress=0
health_gate=0
workload_gate=0
for arg in "$@"; do
    case "$arg" in
        --bench-smoke) bench_smoke=1 ;;
        --bench-gate) bench_gate=1 ;;
        --sanitize-stress) sanitize_stress=1 ;;
        --health-gate) health_gate=1 ;;
        --workload-gate) workload_gate=1 ;;
        *) echo "ci_gate.sh: unknown argument '$arg'" >&2; exit 2 ;;
    esac
done

echo "== graftlint =="
python -m tools.graftlint --format "$fmt" --jobs "$jobs"
lint_rc=$?

echo "== tier-1 pytest =="
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
test_rc=$?

bench_rc=0
if [ "$bench_smoke" -eq 1 ]; then
    echo "== bench smoke (pipelined 50k-row GBM) =="
    sidecar="$(mktemp /tmp/h2o_tpu_bench_smoke.XXXXXX.jsonl)"
    timeout -k 10 900 env JAX_PLATFORMS=cpu \
        H2O_TPU_BENCH_WORKLOADS=airlines \
        H2O_TPU_BENCH_AIRLINES_ROWS=50000 \
        H2O_TPU_PIPELINE=1 \
        H2O_TPU_BENCH_SIDECAR="$sidecar" \
        python bench.py > /dev/null
    bench_rc=$?
    if [ "$bench_rc" -eq 0 ]; then
        python - "$sidecar" <<'EOF'
import json, sys

rec = None
for line in open(sys.argv[1]):
    d = json.loads(line)
    if d.get("workload") == "airlines116m":
        rec = d["record"]
assert rec is not None, "airlines leg record missing from sidecar"
assert rec["forest_parity"] is True, \
    f"pipelined forest NOT bit-equal to the synchronous oracle: {rec}"
assert rec["uncached_compiles_warm"] == 0, \
    f"steady-state uncached compiles: {rec['uncached_compiles_warm']}"
print(json.dumps({"bench_smoke": "ok",
                  "wall_s": rec["wall_s"],
                  "wall_sync_s": rec["wall_sync_s"],
                  "pipeline_speedup_x": rec["pipeline_speedup_x"],
                  "overlap_ratio": rec["overlap_ratio"]}))
EOF
        bench_rc=$?
    fi
    rm -f "$sidecar"
fi

gate_rc=0
if [ "$bench_gate" -eq 1 ]; then
    echo "== bench gate (gbm+glm @ BENCH_r06 config vs baseline bands) =="
    sidecar="$(mktemp /tmp/h2o_tpu_bench_gate.XXXXXX.jsonl)"
    timeout -k 10 1500 env JAX_PLATFORMS=cpu \
        H2O_TPU_BENCH_WORKLOADS=gbm,glm \
        H2O_TPU_BENCH_ROWS=60000 \
        H2O_TPU_BENCH_TREES=100 \
        H2O_TPU_BENCH_SIDECAR="$sidecar" \
        python bench.py > /dev/null
    gate_rc=$?
    if [ "$gate_rc" -eq 0 ]; then
        python tools/bench_gate.py --run "$sidecar"
        gate_rc=$?
    fi
    rm -f "$sidecar"
fi

stress_rc=0
if [ "$sanitize_stress" -eq 1 ]; then
    echo "== sanitize stress (serving+train+sweep, all four arms armed) =="
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest -q \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        "tests/test_sanitizer.py::TestStressSilence::test_serving_train_sweep_stress_stays_silent[locks,guards,transfers,recompiles]" \
        "tests/test_sanitizer.py::TestTransferSanitizer::test_live_h2d_guard_trips_typed_on_cpu_mesh" \
        "tests/test_sanitizer.py::TestTransferSanitizer::test_failpoint_drill_types_and_bundles" \
        "tests/test_sanitizer.py::TestRecompileSanitizer::test_serving_bucket_miss_raises_typed_and_bundles"
    stress_rc=$?
fi

health_rc=0
if [ "$health_gate" -eq 1 ]; then
    echo "== health gate (/3/Health ready -> wedged -> recovered) =="
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        H2O_TPU_WATCHDOG_MS=100 \
        python - <<'EOF'
import json
import time
import urllib.request

from h2o_tpu.api.server import H2OServer
from h2o_tpu.utils import failpoints

srv = H2OServer(port=54941).start()


def health():
    with urllib.request.urlopen(f"{srv.url}/3/Health", timeout=10) as r:
        return json.loads(r.read().decode())


h = health()
assert h["live"] and h["ready"], \
    f"expected ready on boot, degraded: {h['degraded']}"

# wedge: the registered watchdog.trip failpoint force-trips all four
# detectors on the next sweep — nothing is actually wrong, which is the
# point: the gate drills the SIGNAL path, not a real outage
failpoints.arm("watchdog.trip", "raise*4")
deadline = time.time() + 20
while time.time() < deadline:
    h = health()
    if not h["ready"]:
        break
    time.sleep(0.1)
assert not h["ready"], "health never degraded under the armed drill"
reasons = {d["reason"] for d in h["degraded"]}
assert "watchdog-trip" in reasons, f"wrong typed reasons: {reasons}"

# recover: disarm, trips age out after 10 sweep intervals (~1s here)
failpoints.disarm("watchdog.trip")
deadline = time.time() + 30
while time.time() < deadline:
    h = health()
    if h["ready"]:
        break
    time.sleep(0.2)
assert h["ready"], f"health never recovered after disarm: {h['degraded']}"
srv.stop()
print(json.dumps({"health_gate": "ok"}))
EOF
    health_rc=$?
fi

workload_rc=0
if [ "$workload_gate" -eq 1 ]; then
    echo "== workload gate (3-tenant stress, boundary kills, SLO held) =="
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
        H2O_TPU_WORKLOAD_SLOTS=2 \
        H2O_TPU_WORKLOAD_TICK_MS=100 \
        H2O_TPU_CHECKPOINT_SECS=0 \
        H2O_TPU_SANITIZE=recompiles \
        python - <<'EOF'
import json
import threading
import time
import urllib.request

import numpy as np

from h2o_tpu.api.server import H2OServer
from h2o_tpu.frame.frame import Frame
from h2o_tpu.frame.vec import T_CAT, Vec
from h2o_tpu.utils import failpoints

srv = H2OServer(port=54946).start()


def req(method, path, body=None, hdrs=None):
    r = urllib.request.Request(
        srv.url + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json", **(hdrs or {})})
    with urllib.request.urlopen(r, timeout=60) as resp:
        return json.loads(resp.read().decode())


rng = np.random.default_rng(5)
n = 2000
x1 = rng.normal(size=n).astype(np.float32)
x2 = rng.normal(size=n).astype(np.float32)
y = ((x1 - 0.4 * x2 + rng.normal(scale=0.4, size=n)) > 0.1) \
    .astype(np.float32)
fr = Frame.from_dict({"x1": x1, "x2": x2})
fr.add("y", Vec.from_numpy(y, type=T_CAT, domain=["0", "1"]))
fid = str(fr.key)


def build(tenant, prio, rdir=None):
    body = {"training_frame": fid, "response_column": "y", "ntrees": 6,
            "max_depth": 3, "seed": 42, "score_tree_interval": 2}
    if rdir:
        body["auto_recovery_dir"] = rdir
    out = req("POST", "/3/ModelBuilders/gbm", body,
              {"X-H2O-TPU-Tenant": tenant, "X-H2O-TPU-Priority": prio})
    return out["job"]["key"]["name"]


def entry_of(job_key, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for e in req("GET", "/3/Workload")["entries"]:
            if e["job"] == job_key:
                return e["id"]
        time.sleep(0.1)
    raise AssertionError(f"no scheduler entry for {job_key}")


def wait_entry_done(eid, timeout=240):
    deadline = time.time() + timeout
    while time.time() < deadline:
        ent = next(e for e in req("GET", "/3/Workload")["entries"]
                   if e["id"] == eid)
        if ent["state"] in ("DONE", "FAILED", "CANCELLED"):
            return ent
        time.sleep(0.2)
    raise AssertionError(f"entry {eid} never finished")


def wait_job(key, timeout=240):
    deadline = time.time() + timeout
    while time.time() < deadline:
        j = req("GET", f"/3/Jobs/{key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED", "CANCELLED"):
            return j
        time.sleep(0.2)
    raise AssertionError(f"job {key} never finished")


# -- phase 1: kill a managed build at EVERY chunk boundary over the wire.
# The REST job lands PREEMPTED; the scheduler entry must auto-resume and
# finish DONE with >= 1 preemption recorded — no operator action.
for k in (1, 2, 3):
    failpoints.reset()
    failpoints.arm("workload.preempt", f"raise(preempt)@{k}")
    key = build("drill", "batch", rdir=f"/tmp/h2o_tpu_wl_gate_k{k}")
    eid = entry_of(key)
    ent = wait_entry_done(eid)
    failpoints.reset()
    assert ent["state"] == "DONE", f"boundary-{k} kill not healed: {ent}"
    assert ent["preemptions"] >= 1, f"boundary-{k} never preempted: {ent}"
print(json.dumps({"boundary_kills": "ok", "boundaries": 3}))

# -- phase 2: 3-tenant mixed-priority stress with serving scores between
scorer_model = wait_job(build("serving", "interactive"))["dest"]["name"]
stop_scores = threading.Event()
score_errors = []


def score_loop():
    while not stop_scores.is_set():
        try:
            req("POST",
                f"/3/Predictions/models/{scorer_model}/frames/{fid}",
                body={})
        except Exception as e:  # noqa: BLE001
            score_errors.append(repr(e))
            return
        time.sleep(0.05)


scorer = threading.Thread(target=score_loop, daemon=True)
scorer.start()
keys = {t: build(t, p) for t, p in
        (("acme", "interactive"), ("beta", "batch"),
         ("gamma", "background"))}
jobs = {t: wait_job(k) for t, k in keys.items()}
stop_scores.set()
scorer.join(timeout=10)
assert not score_errors, f"serving failed mid-stress: {score_errors[0]}"
for t, j in jobs.items():
    assert j["status"] == "DONE", f"tenant {t} starved/failed: {j}"
    assert j["tenant"] == t, f"tenant stamp lost: {j}"

# the SLO/health plane held through the stress, and the sanitizer arms
# stayed silent: zero violations, zero steady-state recompiles
h = req("GET", "/3/Health")
assert h["live"] and h["ready"], f"health degraded: {h['degraded']}"
metrics = req("GET", "/3/Metrics")["metrics"]
for name in ("sanitizer.violation.count", "serving.recompile.count"):
    v = (metrics.get(name) or {}).get("value")
    assert not v, f"{name} = {v}"
snap = req("GET", "/3/Workload")
assert {"acme", "beta", "gamma"} <= set(snap["tenants"]), snap["tenants"]
prom = urllib.request.urlopen(
    srv.url + "/3/Metrics?format=prometheus", timeout=30).read().decode()
assert 'h2o_tpu_tenant_running_jobs{tenant="acme"}' in prom
srv.stop()
print(json.dumps({"workload_gate": "ok",
                  "tenants": sorted(keys),
                  "preempt_count": metrics["workload.preempt.count"]
                  ["value"]}))
EOF
    workload_rc=$?
fi

echo "== gate: lint rc=${lint_rc}, tests rc=${test_rc}, bench rc=${bench_rc}, bench-gate rc=${gate_rc}, sanitize-stress rc=${stress_rc}, health rc=${health_rc}, workload rc=${workload_rc} =="
if [ "$lint_rc" -ne 0 ] || [ "$test_rc" -ne 0 ] || [ "$bench_rc" -ne 0 ] || [ "$gate_rc" -ne 0 ] || [ "$stress_rc" -ne 0 ] || [ "$health_rc" -ne 0 ] || [ "$workload_rc" -ne 0 ]; then
    exit 1
fi
exit 0
